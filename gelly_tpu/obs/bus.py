"""Process-wide event bus: counters, gauges, histograms, events.

One default :class:`EventBus` exists per process (:func:`get_bus`) so
runtime modules can publish without any wiring — the same stance as the
fault registry in ``engine/faults.py``. Publishing is a locked dict
update (no I/O, no allocation beyond the event dict for :meth:`emit`),
cheap enough to stay always-on at the cadences the runtime publishes at
(per retry, per window close, per checkpoint — never per edge).

Counters and gauges are ALWAYS-ON; histograms (:meth:`EventBus.observe`
into a fixed-memory :class:`~gelly_tpu.obs.histogram.
StreamingHistogram`) and the end-to-end latency watermarks
(``bus.watermarks``, a :class:`~gelly_tpu.obs.watermarks.Watermarks`
ledger) are GUARDED: the engine/ingest hot paths bind them only when a
span tracer is installed or :func:`recording` is on (enable with
:func:`record_metrics` scoped, or :func:`set_recording` for a
long-running server) — the exact ``active_tracer() is not None``
zero-cost-when-disabled discipline the tracer established, so a
disabled run performs no histogram work, not even a clock read.

Counter/gauge names are dotted, ``<subsystem>.<what>``:

====================================  =================================
``resilience.retries``                guarded-boundary retries
``resilience.watchdog_timeouts``      watchdog fires (hung calls)
``resilience.degradations``           native→fallback ladder trips
``resilience.source_restarts``        chunk-source reopenings
``resilience.checkpoints``            completed checkpoint writes
``resilience.checkpoint_misses``      tolerated mid-stream ckpt failures
``resilience.rotation_skipped``       torn-newest prune refusals
``resilience.checkpoint_bytes``       cumulative checkpoint file bytes
``resilience.checkpoint_write_s``     last write latency (gauge)
``faults.injected``                   FaultPlan faults that fired
``coordination.barrier_agreed``       checkpoint barriers resolved
``coordination.prepared``             2PC shard votes written
``coordination.committed``            leader manifest commits
``coordination.leader_elected``       observed leadership changes
``coordination.rejoins``              restart-time re-joins
``coordination.degradations``         degraded-capacity takeovers
``ingest.frames_received``            wire frames decoded by the server
``ingest.frames_rejected``            CRC-mismatch / gap / malformed
``ingest.frames_truncated``           torn frames (conn died mid-frame)
``ingest.frames_duplicate``           reconnect replays dropped+re-acked
``ingest.chunks_enqueued``            payloads staged for the consumer
``ingest.bytes_received``             cumulative wire bytes in
``ingest.acks_sent``                  durability acks pushed to clients
``ingest.backpressure_engaged``       PAUSE engagements (event)
``ingest.staged_depth``               server staging queue depth (gauge)
``ingest.paused``                     1 while PAUSEd (gauge)
``ingest.data_frames_raw``            raw-edge DATA frames staged
``ingest.data_frames_compressed``     client-side-compressed
                                      DATA_COMPRESSED frames staged
                                      (zero server-side compress)
``ingest.frames_sent``                client DATA frames transmitted
``ingest.frames_resent``              client retransmits after rewind
``ingest.pauses_received``            PAUSE frames seen by the client
``ingest.rejects_received``           REJECT frames seen by the client
``ingest.reshards``                   routing-table re-shard events
``ingest.chunks_unroutable``          tenant-router payloads dropped
                                      (unknown tenant, no default)
``ingest.chunks_invalid``             tenant-router payloads dropped
                                      (bad ids/shapes/finished tenant)
``ingest.stats_requests``             STATS introspection frames
                                      answered (read-only; never
                                      advances DATA sequencing)
``ingest.auth_challenges``            AUTH_CHALLENGE nonces issued to
                                      unauthenticated HELLOs
``ingest.auth_failures``              connections refused by the
                                      pre-shared-key gate (bad/missing
                                      proof, or data before auth)
``ingest.nacks_sent``                 terminal NACK frames sent (QoS
                                      shed streams; seq = durable pos)
``ingest.nacks_received``             NACK frames seen by the client
                                      (its stream was shed server-side)
``ingest.frames_shed``                DATA frames dropped on arrival
                                      because their tenant's stream is
                                      shed (never staged, never acked)
``ingest.frames_stacked``             STACKED frames admitted (K
                                      payloads behind one header/CRC,
                                      staged as ONE unit)
``ingest.stack_flush_size``           client stack flushes fired by the
                                      count ceiling (buffer hit
                                      ``stack=K``)
``ingest.stack_flush_bytes``          client stack flushes fired by the
                                      byte ceiling (``stack_bytes=``)
``ingest.stack_flush_age``            client stack flushes fired by the
                                      age deadline (``stack_ms=``);
                                      tail drains on flush()/close()
                                      are untagged
``engine.units_folded``               pipeline units retired by a fold
``engine.chunks_folded``              chunks inside those units
``engine.edges_folded``               valid edges (tracer-enabled runs)
``engine.windows_closed``             merge windows closed
``engine.window_dirty_rows``          dirty count at last delta close
``engine.dirty_rows_gathered``        delta-close rows moved (S*bucket),
                                      cumulative
``engine.checkpoint_bytes``           aggregate-path checkpoint bytes
``engine.throughput.edges``           pipelined-run edges folded (gauge)
``engine.throughput.edges_per_sec``   running fold rate (gauge)
``stage.fold_dispatch.busy_s``        per-stage busy seconds at executor
                                      teardown — one
                                      ``<prefix>.<stage>.busy_s`` gauge
                                      per StageTimer stage
``pipeline.staged_depth``             compress→H2D queue depth (gauge)
``pipeline.h2d_depth``                H2D→fold queue depth (gauge)
``tenants.active``                    live (not-done) tenants (gauge)
``tenants.queue_depth``               total queued tenant chunks (gauge)
``tenants.starved_windows``           live-tenant lanes dispatched as
                                      masked no-ops (tenant had no
                                      pending chunk at batch build)
``tenants.dispatches``                vmapped tenant-batch dispatches
``tenants.chunks_folded``             tenant chunks those advanced
``tenants.windows_closed``            tenant merge windows closed
``tenants.checkpoints``               per-tenant checkpoint writes
``tenants.checkpoint_bytes``          cumulative tenant ckpt bytes
``tenants.compressed_dispatches``     vmapped fold_codec dispatches
                                      (compressed tiers folding
                                      producer-compressed payloads)
``tenants.reclaims``                  idle-lane reclamation events
                                      (tier lane stack halved)
``tenants.lanes_reclaimed``           lanes freed by idle-lane
                                      reclamation, cumulative
``qos.rate_limited``                  ladder OK→LIMITED transitions
                                      (tenant over its backlog budget)
``qos.limit_cleared``                 LIMITED→OK recoveries (backlog
                                      back under budget)
``qos.parked``                        LIMITED→PARKED transitions (lane
                                      freed at the next safe window
                                      boundary; snapshots stay live)
``qos.unparked``                      PARKED→LIMITED re-admissions
                                      (active pressure drained below
                                      the un-park threshold)
``qos.shed``                          PARKED→SHED terminations (parked
                                      queue exceeded shed_queue_depth;
                                      typed NACK on the wire)
``qos.chunks_dropped``                queued chunks discarded by shed
                                      transitions, cumulative
``qos.admissions_refused``            admit() calls refused at the
                                      backlog-age ceiling
                                      (admission="refuse")
``qos.admissions_queued``             admit() calls parked in the
                                      waiting line (admission="queue")
``qos.admissions_resumed``            queued admissions completed once
                                      pressure fell under the ceiling
``qos.limited_tenants``               tenants at LIMITED (gauge)
``qos.parked_tenants``                tenants at PARKED (gauge)
``qos.shed_tenants``                  tenants at SHED (gauge)
``multiquery.runs``                   fused multi-query runs started
``multiquery.fused_queries``          queries riding the active fused
                                      plan (gauge)
``multiquery.compressed_chunks``      chunks through the fused
                                      shared-compress stage (one
                                      multi-query payload per chunk)
``multiquery.emissions``              per-query emissions published
                                      (Q per window close)
``multiquery.snapshot_reads``         live per-query snapshot reads
                                      answered
``sharded_cc.window_dirty_rows``      dirty entries at last emission
``sharded_cc.window_dirty_max_shard`` max per-shard dirty count (gauge)
``sharded_cc.emissions_dense``        window closes emitting full labels
``sharded_cc.emissions_sparse``       window closes emitting dirty pairs
``sharded_cc.dirty_rows_gathered``    dirty rows pulled D2H, cumulative
``engine.backlog_age_s``              oldest unretired ingress stamp's
                                      age — the single-stream low
                                      watermark (gauge; per-tenant
                                      twins publish as
                                      ``tenants.t<tid>.backlog_age_s``)
``tenants.backlog_age_max_s``         worst per-tenant backlog age
                                      (gauge — the QoS admission
                                      headline)
``obs.flight_dumps``                  flight-recorder trace dumps
                                      written (dump_on triggers)
``windows.panes_closed``              pane closes on the windowed ring
                                      (one per merge-window boundary)
``windows.combine_dispatches``        two-stack ``combine`` dispatches
                                      paid by the ring — O(1) amortized
                                      per pane close regardless of W
``windows.evicted_slots``             compact-id slots reclaimed by TTL
                                      decay, cumulative
``windows.snapshot_reads``            windowed ``snapshot()`` epoch
                                      handles served
``windows.ring_live``                 panes currently live in the ring
                                      (gauge; ≤ W)
``windows.live_slots``                compact-id slots assigned after
                                      the pane's TTL sweep (gauge — the
                                      bounded steady-state capacity)
``slo.breaching``                     SLO instances currently in breach
                                      (gauge; the heartbeat's
                                      ``slo_breaching=`` source)
``slo.fold_p99_ms.burn_rate``         breaching fraction of the spec's
                                      rolling window, 0..1 (gauge; one
                                      ``slo.<key>.burn_rate`` per spec
                                      instance, ``<key>`` suffixed
                                      ``.t<tid>`` for per-tenant SLOs)
``slo.breach``                        healthy→breach crossings (event;
                                      fields ``slo``/``tenant``/
                                      ``value``/``threshold``/
                                      ``burn_rate`` — the push-alert
                                      and QoS admission signal)
``slo.recovered``                     breach→healthy crossings (event,
                                      same fields)
``alerts.component_merge``            summary-delta watch saw the
                                      component count drop — a merge
                                      happened (event)
``alerts.degree_spike``               max degree jumped past
                                      ``spike_factor`` × its trailing
                                      EMA (event)
``alerts.subscriptions``              SUBSCRIBE filters accepted,
                                      cumulative
``alerts.subscribers``                live alert subscriptions across
                                      all connections (gauge)
``alerts.pushed``                     ALERT frames written to
                                      subscribed clients
``alerts.dropped``                    ALERT frames lost to a dead
                                      connection — the best-effort
                                      delivery contract's loss counter
``ingest.alerts_received``            ALERT frames consumed by a
                                      client's reader loop
====================================  =================================

Histogram names (``bus.observe(name, value_ms)`` — latency
distributions in MILLISECONDS, snapshot as p50/p90/p99/max; recorded
only when a tracer is installed or :func:`recording` is on):

====================================  =================================
``engine.fold_dispatch_ms``           per-unit fold dispatch wall
``engine.merge_emit_ms``              merge-window close + emission
                                      barrier wall
``engine.e2e_ingress_to_fold_ms``     chunk ingress (wire receive /
                                      reader parse) → fold dispatch;
                                      per-tenant twins publish as
                                      ``tenants.t<tid>.…`` via the
                                      same suffix
``engine.e2e_ingress_to_durable_ms``  chunk ingress → covering
                                      checkpoint durable (window close
                                      on runs without a checkpoint
                                      path); per-tenant twins as above
``resilience.checkpoint_write_ms``    checkpoint write wall — one
                                      ``<prefix>.checkpoint_write_ms``
                                      histogram per checkpoint writer
                                      (engine/resilience/tenants), via
                                      :func:`publish_checkpoint`
``ingest.receive_to_stage_ms``        wire frame fully received →
                                      staged for the consumer
``ingest.chunks_per_stacked_frame``   payload COUNT (not ms) carried by
                                      each admitted STACKED frame — the
                                      realized coalescing factor K
                                      (flush-policy tails drag it below
                                      the configured ``stack=``)
``tenants.round_ms``                  one multi-tenant scheduling
                                      round's batched fold dispatch
``multiquery.emit_ms``                fused emission snapshot
                                      publication at a window close
                                      (lock wait + swap — the reader-
                                      contention signal; the window's
                                      compute wall is merge_emit_ms)
``windows.pane_close_ms``             windowed pane close wall — pane
                                      capture + ring push + suffix
                                      query + transform (scales with
                                      pane size, not window length)
====================================  =================================

Tests that need isolation wrap the block in :func:`scope`, which swaps
a fresh bus in for the dynamic extent — publishers always resolve the
bus at call time (``get_bus()``), so the swap is complete.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Callable, Iterator

import contextlib


class EventBus:
    """Thread-safe counters + gauges + histograms + subscriber fan-out.

    - :meth:`inc` — add to a (float-valued) counter;
    - :meth:`gauge` — set a last-value gauge;
    - :meth:`observe` — record a sample into a named
      :class:`~gelly_tpu.obs.histogram.StreamingHistogram` (created on
      first observation; fixed memory forever after);
    - :meth:`emit` — publish a structured event: bumps the
      ``<name>`` counter, records an instant event into the active span
      tracer (if one is installed — BEFORE the subscriber fan-out, so a
      flight-recorder dump triggered by the event captures its own
      instant), and forwards the event dict to subscribers.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: dict[str, float] = defaultdict(float)
        self.gauges: dict[str, float] = {}
        self.histograms: dict = {}
        from .watermarks import Watermarks

        # The e2e-latency ledger rides the bus so scope() isolates it
        # with the counters (see obs/watermarks.py).
        self.watermarks = Watermarks()
        self._subs: list[Callable[[str, dict], None]] = []

    def inc(self, name: str, n: float = 1) -> None:
        with self._lock:
            self.counters[name] += n

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        """Record one sample into the named histogram. Call sites on
        hot paths must be guarded (tracer installed or
        :func:`recording` on) — see the module docstring."""
        with self._lock:
            h = self.histograms.get(name)
            if h is None:
                from .histogram import StreamingHistogram

                h = self.histograms[name] = StreamingHistogram()
        h.record(value)

    def histogram(self, name: str):
        """The named :class:`StreamingHistogram`, or None if nothing
        was ever observed into it."""
        with self._lock:
            return self.histograms.get(name)

    def quantile(self, name: str, q: float, default: float = 0.0) -> float:
        """Convenience quantile read (``default`` when the histogram
        does not exist) — the heartbeat's p99 source."""
        h = self.histogram(name)
        return h.quantile(q) if h is not None else default

    def emit(self, name: str, **fields) -> None:
        with self._lock:
            self.counters[name] += 1
            subs = list(self._subs)
        # Mirror onto the trace timeline FIRST: a flight-recorder dump
        # subscribed to this event must find the event's own instant in
        # the ring it exports. Imported lazily (bus must stay importable
        # first — tracing imports nothing back from here).
        from .tracing import active_tracer

        tr = active_tracer()
        if tr is not None:
            tr.instant(name, **fields)
        for fn in subs:
            try:
                fn(name, fields)
            except Exception:  # noqa: BLE001
                # A raising subscriber must never turn observability into
                # a runtime fault at the PUBLISHER's call site (the
                # watchdog/retry/fault-injection paths all emit).
                import logging

                logging.getLogger("gelly_tpu.obs").exception(
                    "event-bus subscriber failed on %r", name)

    def subscribe(self, fn: Callable[[str, dict], None]) -> Callable[[], None]:
        """Register ``fn(name, fields)`` for every :meth:`emit`; returns
        an unsubscribe callable."""
        with self._lock:
            self._subs.append(fn)

        def unsubscribe() -> None:
            with self._lock:
                if fn in self._subs:
                    self._subs.remove(fn)

        return unsubscribe

    def snapshot(self) -> dict:
        """Point-in-time copy: counters, gauges, histogram quantile
        snapshots and per-stream watermark states — all plain JSON
        types (trace ``otherData`` and the STATS endpoint embed it
        verbatim)."""
        with self._lock:
            counters = dict(self.counters)
            gauges = dict(self.gauges)
            hists = dict(self.histograms)
        return {
            "counters": counters,
            "gauges": gauges,
            "histograms": {k: h.snapshot() for k, h in hists.items()},
            "watermarks": self.watermarks.snapshot(),
        }


def publish_checkpoint(bus: EventBus, prefix: str, path: str,
                       t0: float | None = None) -> int:
    """Shared checkpoint-durability publishing (used by ALL checkpoint
    writers — ``engine/resilience.CheckpointManager``, the aggregate
    path's ``maybe_checkpoint`` and the tenant engine): bump
    ``<prefix>.checkpoints`` and ``<prefix>.checkpoint_bytes`` (file
    size; 0 when unreadable), and when ``t0`` (``time.perf_counter()``
    at write start) is given, gauge ``<prefix>.checkpoint_write_s`` —
    plus, when telemetry recording is on (tracer installed or
    :func:`recording`), the ``<prefix>.checkpoint_write_ms``
    write-latency HISTOGRAM. Returns the byte count."""
    import os
    import time

    try:
        size = os.path.getsize(path)
    except OSError:
        size = 0
    bus.inc(f"{prefix}.checkpoints")
    bus.inc(f"{prefix}.checkpoint_bytes", size)
    if t0 is not None:
        dt = time.perf_counter() - t0
        bus.gauge(f"{prefix}.checkpoint_write_s", round(dt, 6))
        if telemetry_on():
            bus.observe(f"{prefix}.checkpoint_write_ms", dt * 1e3)
    return size


_DEFAULT = EventBus()
_CURRENT: EventBus = _DEFAULT
_SWAP_LOCK = threading.Lock()
# Histogram/watermark recording enable (see module docstring): a
# nesting count for record_metrics() scopes plus an absolute switch for
# long-running servers (the example's --serve --stats).
_RECORD_DEPTH = 0
_RECORD_FORCED = False


def recording() -> bool:
    """True when histogram/watermark recording is enabled — THE
    disabled-path check next to ``active_tracer() is not None``: hot
    paths bind ``bus.observe``/``bus.watermarks`` once per run only
    when one of the two is on."""
    return _RECORD_DEPTH > 0 or _RECORD_FORCED


def telemetry_on() -> bool:
    """THE serving-plane telemetry guard, shared by every recording
    site (engine/resilience/tenants/ingest): histograms and watermarks
    record when :func:`recording` is on OR a span tracer is installed.
    One definition, so a future change to the enablement rule cannot
    silently split the zero-cost-when-disabled contract across
    hand-copied guards."""
    from .tracing import active_tracer

    return recording() or active_tracer() is not None


def set_recording(on: bool) -> None:
    """Absolute recording switch (idempotent) for long-running
    processes; scoped code should prefer :func:`record_metrics`."""
    global _RECORD_FORCED
    with _SWAP_LOCK:
        _RECORD_FORCED = bool(on)


@contextlib.contextmanager
def record_metrics() -> Iterator[None]:
    """Enable histogram/watermark recording for the dynamic extent
    (nests; same shape as :func:`scope`)."""
    global _RECORD_DEPTH
    with _SWAP_LOCK:
        _RECORD_DEPTH += 1
    try:
        yield
    finally:
        with _SWAP_LOCK:
            _RECORD_DEPTH -= 1


def get_bus() -> EventBus:
    """The process-wide bus (or the innermost :func:`scope` bus)."""
    return _CURRENT


@contextlib.contextmanager
def scope(bus: EventBus | None = None) -> Iterator[EventBus]:
    """Swap a fresh (or given) bus in for the dynamic extent — test
    isolation without publishers needing to thread a bus parameter."""
    global _CURRENT
    new = bus if bus is not None else EventBus()
    with _SWAP_LOCK:
        prev, _CURRENT = _CURRENT, new
    try:
        yield new
    finally:
        with _SWAP_LOCK:
            _CURRENT = prev
