"""Process-wide event bus: counters, gauges, structured events.

One default :class:`EventBus` exists per process (:func:`get_bus`) so
runtime modules can publish without any wiring — the same stance as the
fault registry in ``engine/faults.py``. Publishing is a locked dict
update (no I/O, no allocation beyond the event dict for :meth:`emit`),
cheap enough to stay always-on at the cadences the runtime publishes at
(per retry, per window close, per checkpoint — never per edge).

Counter/gauge names are dotted, ``<subsystem>.<what>``:

====================================  =================================
``resilience.retries``                guarded-boundary retries
``resilience.watchdog_timeouts``      watchdog fires (hung calls)
``resilience.degradations``           native→fallback ladder trips
``resilience.source_restarts``        chunk-source reopenings
``resilience.checkpoints``            completed checkpoint writes
``resilience.checkpoint_misses``      tolerated mid-stream ckpt failures
``resilience.rotation_skipped``       torn-newest prune refusals
``resilience.checkpoint_bytes``       cumulative checkpoint file bytes
``resilience.checkpoint_write_s``     last write latency (gauge)
``faults.injected``                   FaultPlan faults that fired
``coordination.barrier_agreed``       checkpoint barriers resolved
``coordination.prepared``             2PC shard votes written
``coordination.committed``            leader manifest commits
``coordination.leader_elected``       observed leadership changes
``coordination.rejoins``              restart-time re-joins
``coordination.degradations``         degraded-capacity takeovers
``ingest.frames_received``            wire frames decoded by the server
``ingest.frames_rejected``            CRC-mismatch / gap / malformed
``ingest.frames_truncated``           torn frames (conn died mid-frame)
``ingest.frames_duplicate``           reconnect replays dropped+re-acked
``ingest.chunks_enqueued``            payloads staged for the consumer
``ingest.bytes_received``             cumulative wire bytes in
``ingest.acks_sent``                  durability acks pushed to clients
``ingest.backpressure_engaged``       PAUSE engagements (event)
``ingest.staged_depth``               server staging queue depth (gauge)
``ingest.paused``                     1 while PAUSEd (gauge)
``ingest.data_frames_raw``            raw-edge DATA frames staged
``ingest.data_frames_compressed``     client-side-compressed
                                      DATA_COMPRESSED frames staged
                                      (zero server-side compress)
``ingest.frames_sent``                client DATA frames transmitted
``ingest.frames_resent``              client retransmits after rewind
``ingest.pauses_received``            PAUSE frames seen by the client
``ingest.rejects_received``           REJECT frames seen by the client
``ingest.reshards``                   routing-table re-shard events
``ingest.chunks_unroutable``          tenant-router payloads dropped
                                      (unknown tenant, no default)
``ingest.chunks_invalid``             tenant-router payloads dropped
                                      (bad ids/shapes/finished tenant)
``engine.units_folded``               pipeline units retired by a fold
``engine.chunks_folded``              chunks inside those units
``engine.edges_folded``               valid edges (tracer-enabled runs)
``engine.windows_closed``             merge windows closed
``engine.window_dirty_rows``          dirty count at last delta close
``engine.dirty_rows_gathered``        delta-close rows moved (S*bucket),
                                      cumulative
``engine.checkpoint_bytes``           aggregate-path checkpoint bytes
``engine.throughput.edges``           pipelined-run edges folded (gauge)
``engine.throughput.edges_per_sec``   running fold rate (gauge)
``stage.fold_dispatch.busy_s``        per-stage busy seconds at executor
                                      teardown — one
                                      ``<prefix>.<stage>.busy_s`` gauge
                                      per StageTimer stage
``pipeline.staged_depth``             compress→H2D queue depth (gauge)
``pipeline.h2d_depth``                H2D→fold queue depth (gauge)
``tenants.active``                    live (not-done) tenants (gauge)
``tenants.queue_depth``               total queued tenant chunks (gauge)
``tenants.starved_windows``           live-tenant lanes dispatched as
                                      masked no-ops (tenant had no
                                      pending chunk at batch build)
``tenants.dispatches``                vmapped tenant-batch dispatches
``tenants.chunks_folded``             tenant chunks those advanced
``tenants.windows_closed``            tenant merge windows closed
``tenants.checkpoints``               per-tenant checkpoint writes
``tenants.checkpoint_bytes``          cumulative tenant ckpt bytes
``tenants.compressed_dispatches``     vmapped fold_codec dispatches
                                      (compressed tiers folding
                                      producer-compressed payloads)
``tenants.reclaims``                  idle-lane reclamation events
                                      (tier lane stack halved)
``tenants.lanes_reclaimed``           lanes freed by idle-lane
                                      reclamation, cumulative
``multiquery.runs``                   fused multi-query runs started
``multiquery.fused_queries``          queries riding the active fused
                                      plan (gauge)
``multiquery.compressed_chunks``      chunks through the fused
                                      shared-compress stage (one
                                      multi-query payload per chunk)
``multiquery.emissions``              per-query emissions published
                                      (Q per window close)
``multiquery.snapshot_reads``         live per-query snapshot reads
                                      answered
``sharded_cc.window_dirty_rows``      dirty entries at last emission
``sharded_cc.window_dirty_max_shard`` max per-shard dirty count (gauge)
``sharded_cc.emissions_dense``        window closes emitting full labels
``sharded_cc.emissions_sparse``       window closes emitting dirty pairs
``sharded_cc.dirty_rows_gathered``    dirty rows pulled D2H, cumulative
====================================  =================================

Tests that need isolation wrap the block in :func:`scope`, which swaps
a fresh bus in for the dynamic extent — publishers always resolve the
bus at call time (``get_bus()``), so the swap is complete.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Callable, Iterator

import contextlib


class EventBus:
    """Thread-safe counters + gauges + subscriber fan-out.

    - :meth:`inc` — add to a (float-valued) counter;
    - :meth:`gauge` — set a last-value gauge;
    - :meth:`emit` — publish a structured event: bumps the
      ``<name>`` counter, forwards the event dict to subscribers, and
      records an instant event into the active span tracer (if one is
      installed) so exported traces show retries/faults/degradations on
      the timeline.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: dict[str, float] = defaultdict(float)
        self.gauges: dict[str, float] = {}
        self._subs: list[Callable[[str, dict], None]] = []

    def inc(self, name: str, n: float = 1) -> None:
        with self._lock:
            self.counters[name] += n

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = value

    def emit(self, name: str, **fields) -> None:
        with self._lock:
            self.counters[name] += 1
            subs = list(self._subs)
        for fn in subs:
            try:
                fn(name, fields)
            except Exception:  # noqa: BLE001
                # A raising subscriber must never turn observability into
                # a runtime fault at the PUBLISHER's call site (the
                # watchdog/retry/fault-injection paths all emit).
                import logging

                logging.getLogger("gelly_tpu.obs").exception(
                    "event-bus subscriber failed on %r", name)
        # Mirror onto the trace timeline. Imported lazily (bus must stay
        # importable first — tracing imports nothing back from here).
        from .tracing import active_tracer

        tr = active_tracer()
        if tr is not None:
            tr.instant(name, **fields)

    def subscribe(self, fn: Callable[[str, dict], None]) -> Callable[[], None]:
        """Register ``fn(name, fields)`` for every :meth:`emit`; returns
        an unsubscribe callable."""
        with self._lock:
            self._subs.append(fn)

        def unsubscribe() -> None:
            with self._lock:
                if fn in self._subs:
                    self._subs.remove(fn)

        return unsubscribe

    def snapshot(self) -> dict:
        """Point-in-time copy ``{"counters": {...}, "gauges": {...}}``."""
        with self._lock:
            return {
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
            }


def publish_checkpoint(bus: EventBus, prefix: str, path: str,
                       t0: float | None = None) -> int:
    """Shared checkpoint-durability publishing (used by BOTH checkpoint
    writers — ``engine/resilience.CheckpointManager`` and the aggregate
    path's ``maybe_checkpoint``): bump ``<prefix>.checkpoints`` and
    ``<prefix>.checkpoint_bytes`` (file size; 0 when unreadable), and
    when ``t0`` (``time.perf_counter()`` at write start) is given, gauge
    ``<prefix>.checkpoint_write_s``. Returns the byte count."""
    import os
    import time

    try:
        size = os.path.getsize(path)
    except OSError:
        size = 0
    bus.inc(f"{prefix}.checkpoints")
    bus.inc(f"{prefix}.checkpoint_bytes", size)
    if t0 is not None:
        bus.gauge(f"{prefix}.checkpoint_write_s",
                  round(time.perf_counter() - t0, 6))
    return size


_DEFAULT = EventBus()
_CURRENT: EventBus = _DEFAULT
_SWAP_LOCK = threading.Lock()


def get_bus() -> EventBus:
    """The process-wide bus (or the innermost :func:`scope` bus)."""
    return _CURRENT


@contextlib.contextmanager
def scope(bus: EventBus | None = None) -> Iterator[EventBus]:
    """Swap a fresh (or given) bus in for the dynamic extent — test
    isolation without publishers needing to thread a bus parameter."""
    global _CURRENT
    new = bus if bus is not None else EventBus()
    with _SWAP_LOCK:
        prev, _CURRENT = _CURRENT, new
    try:
        yield new
    finally:
        with _SWAP_LOCK:
            _CURRENT = prev
