"""Fixed-memory streaming latency histograms (log-bucketed).

A :class:`StreamingHistogram` is the distribution-valued sibling of the
bus's counters and gauges: ``record(value)`` lands the sample in one of
a FIXED number of logarithmic buckets (8 sub-buckets per power of two,
so quantile estimates carry <= ~9% relative error by construction),
``quantile(q)``/``snapshot()`` read p50/p90/p99/max at any time, and
``merge(other)`` folds two histograms bucket-wise — the property that
lets per-shard or per-incarnation histograms aggregate into one fleet
view without ever shipping raw samples.

Memory is O(buckets) forever — a week-long stream costs exactly the
same bytes as the first window — which is why the serving plane records
distributions here instead of appending samples anywhere.

Threading: ``record`` takes a short lock around two integer adds; the
cadence is per pipeline unit / window close / checkpoint (never per
edge), so the lock is uncontended in practice. Reads snapshot under the
same lock. The zero-cost-when-disabled contract lives at the CALL
sites, not here: engine/ingest code binds ``bus.observe`` only when a
tracer is installed or :func:`gelly_tpu.obs.bus.recording` is on, so a
disabled run never reaches this module (not even for a clock read).
"""

from __future__ import annotations

import math
import threading

# Bucket geometry: SUB sub-buckets per octave (power of two), exponents
# spanning 2^MIN_EXP .. 2^MAX_EXP. With values in milliseconds that is
# ~1 ns .. ~17 years — anything outside clamps into the edge buckets
# (counted, never dropped).
_SUB = 8
_MIN_EXP = -20
_MAX_EXP = 44
_N_BUCKETS = (_MAX_EXP - _MIN_EXP) * _SUB


def _bucket_of(value: float) -> int:
    """Log-bucket index of ``value``: octave from ``frexp``, linear
    sub-bucket from the mantissa (HdrHistogram's trick — no log() call
    on the record path)."""
    if value <= 0.0 or value != value:  # <= 0 and NaN land in bucket 0
        return 0
    m, e = math.frexp(value)  # value = m * 2**e, m in [0.5, 1)
    idx = (e - 1 - _MIN_EXP) * _SUB + int((m - 0.5) * 2 * _SUB)
    if idx < 0:
        return 0
    if idx >= _N_BUCKETS:
        return _N_BUCKETS - 1
    return idx


def _bucket_upper(idx: int) -> float:
    """Upper edge of bucket ``idx`` — the quantile estimate returned
    for samples that fell in it (a conservative bound: the reported
    pXX is never below the true one by more than one bucket width)."""
    octave, sub = divmod(idx, _SUB)
    return math.ldexp(0.5 + (sub + 1) / (2 * _SUB), octave + 1 + _MIN_EXP)


class StreamingHistogram:
    """Mergeable fixed-memory log-bucketed histogram.

    - :meth:`record` — O(1), no allocation (bucket array pre-built);
    - :meth:`quantile` — bucket-walk estimate, upper-edge convention;
    - :meth:`merge` — bucket-wise sum (associative + commutative);
    - :meth:`snapshot` — ``{count, sum, min, max, p50, p90, p99}``,
      plain floats (JSON-ready — the STATS endpoint and trace
      ``otherData`` embed it verbatim).

    ``min``/``max`` are EXACT (tracked outside the buckets); quantiles
    are bucket-resolution estimates. Non-positive and NaN samples clamp
    into the lowest bucket rather than raising — telemetry must never
    fault the path it measures.
    """

    __slots__ = ("_lock", "_counts", "count", "total", "vmin", "vmax")

    def __init__(self):
        self._lock = threading.Lock()
        self._counts = [0] * _N_BUCKETS
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf

    def record(self, value: float) -> None:
        value = float(value)
        idx = _bucket_of(value)
        with self._lock:
            self._counts[idx] += 1
            self.count += 1
            self.total += value
            if value < self.vmin:
                self.vmin = value
            if value > self.vmax:
                self.vmax = value

    def merge(self, other: "StreamingHistogram") -> "StreamingHistogram":
        """Fold ``other`` into this histogram (bucket-wise); returns
        self. Lock order: other's counts are snapshotted first, so two
        cross-merges cannot deadlock."""
        with other._lock:
            counts = list(other._counts)
            ocount, ototal = other.count, other.total
            omin, omax = other.vmin, other.vmax
        with self._lock:
            for i, c in enumerate(counts):
                if c:
                    self._counts[i] += c
            self.count += ocount
            self.total += ototal
            if omin < self.vmin:
                self.vmin = omin
            if omax > self.vmax:
                self.vmax = omax
        return self

    @staticmethod
    def _quantile_of(counts, count, vmin, vmax, q: float) -> float:
        """Quantile estimate over one consistent (counts, count, min,
        max) view — callers take it under the lock so a snapshot's
        quantiles describe exactly the population its count reports."""
        if count == 0:
            return 0.0
        rank = q * count
        seen = 0
        for i, c in enumerate(counts):
            seen += c
            if seen >= rank and c:
                # Clamp the bucket-edge estimate to the exact
                # extrema: a one-sample histogram reports its value.
                return float(min(max(_bucket_upper(i), vmin), vmax))
        return float(vmax)

    def quantile(self, q: float) -> float:
        """Estimate the ``q``-quantile (0 <= q <= 1); 0.0 when empty."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        with self._lock:
            return self._quantile_of(self._counts, self.count,
                                     self.vmin, self.vmax, q)

    def snapshot(self) -> dict:
        # ONE lock acquisition covers every field read AND the quantile
        # walks: the STATS endpoint reads this live mid-stream, and a
        # record() interleaving between per-field reads would otherwise
        # report e.g. a count over one population and a p99 over
        # another.
        with self._lock:
            if self.count == 0:
                return {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
                        "p50": 0.0, "p90": 0.0, "p99": 0.0}
            counts = list(self._counts)
            count, total = self.count, self.total
            vmin, vmax = self.vmin, self.vmax
        return {
            "count": count,
            "sum": round(total, 6),
            "min": round(vmin, 6),
            "max": round(vmax, 6),
            "p50": round(self._quantile_of(counts, count, vmin, vmax,
                                           0.50), 6),
            "p90": round(self._quantile_of(counts, count, vmin, vmax,
                                           0.90), 6),
            "p99": round(self._quantile_of(counts, count, vmin, vmax,
                                           0.99), 6),
        }

    def __len__(self) -> int:
        return self.count

    def __repr__(self) -> str:  # debugging aid only
        s = self.snapshot()
        return (f"StreamingHistogram(count={s['count']}, p50={s['p50']}, "
                f"p99={s['p99']}, max={s['max']})")
