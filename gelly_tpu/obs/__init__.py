"""Observability runtime: span tracing, event bus, trace export.

The reference delegates ALL of this to Flink's runtime (web UI, metrics
registry, checkpoint stats — SURVEY.md §5: the repo's sole in-tree
instrument is a ``getNetRuntime()`` printout). The TPU-native framework
re-owns it:

- :mod:`~gelly_tpu.obs.bus` — a process-wide :class:`EventBus` of
  counters, gauges and structured events. Runtime modules
  (``engine/resilience.py``, ``engine/faults.py``, the pipelined
  executor, ``parallel/sharded_cc.py``) publish here instead of
  log-text-only, so tests and bench assert on runtime behavior
  programmatically (``get_bus().counters[...]``) rather than grepping
  logs.
- :mod:`~gelly_tpu.obs.tracing` — a low-overhead per-unit
  :class:`SpanTracer`: every pipeline unit carries its id through
  produce → compress (worker K) → H2D (buffer slot) → fold →
  merge-window close → checkpoint, each span recording thread/worker,
  queue depth and payload sizes into a bounded ring buffer. Disabled
  (the default) the unit path performs ZERO extra allocations — every
  call site is guarded by a plain ``tracer is not None`` check on a
  generator-local binding.
- :mod:`~gelly_tpu.obs.export` — Chrome-trace-event JSON
  (Perfetto-loadable): one track per stage/worker, instant events for
  retries/faults/window closes, and the tracer's ``trace_id`` in
  ``otherData`` so a device-side ``jax.profiler`` trace captured around
  the same run (``utils.metrics.trace(log_dir, tracer=...)``) can be
  laid alongside it.
- :mod:`~gelly_tpu.obs.heartbeat` — a periodic progress line (eps,
  queue depths, last-retired position, backlog-age watermark, p99 fold
  dispatch) for long streams.
- :mod:`~gelly_tpu.obs.histogram` — fixed-memory log-bucketed
  :class:`StreamingHistogram` latency distributions
  (``bus.observe(name, ms)``), recorded at the serving plane's hot
  boundaries only when a tracer is installed or
  :func:`~gelly_tpu.obs.bus.recording` is on.
- :mod:`~gelly_tpu.obs.watermarks` — per-stream/per-tenant end-to-end
  latency ledgers (``bus.watermarks``): ingress stamps ride the
  exactly-once positions through fold and durability, and the oldest
  unretired stamp IS the backlog-age low watermark QoS gates on.
- :mod:`~gelly_tpu.obs.status` — the live STATS introspection endpoint:
  ``python -m gelly_tpu.obs.status HOST:PORT`` asks a running ingest
  server for a JSON snapshot mid-stream.
"""

from .bus import (
    EventBus,
    get_bus,
    record_metrics,
    recording,
    scope,
    set_recording,
)
from .export import (
    to_chrome_trace,
    validate_chrome_trace,
    write_chrome_trace,
)
from .heartbeat import Heartbeat
from .histogram import StreamingHistogram
from .tracing import SpanTracer, active_tracer, install
from .watermarks import Watermarks

__all__ = [
    "EventBus",
    "get_bus",
    "scope",
    "recording",
    "record_metrics",
    "set_recording",
    "SpanTracer",
    "active_tracer",
    "install",
    "to_chrome_trace",
    "validate_chrome_trace",
    "write_chrome_trace",
    "Heartbeat",
    "StreamingHistogram",
    "Watermarks",
]
