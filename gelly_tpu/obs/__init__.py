"""Observability runtime: span tracing, event bus, trace export.

The reference delegates ALL of this to Flink's runtime (web UI, metrics
registry, checkpoint stats — SURVEY.md §5: the repo's sole in-tree
instrument is a ``getNetRuntime()`` printout). The TPU-native framework
re-owns it:

- :mod:`~gelly_tpu.obs.bus` — a process-wide :class:`EventBus` of
  counters, gauges and structured events. Runtime modules
  (``engine/resilience.py``, ``engine/faults.py``, the pipelined
  executor, ``parallel/sharded_cc.py``) publish here instead of
  log-text-only, so tests and bench assert on runtime behavior
  programmatically (``get_bus().counters[...]``) rather than grepping
  logs.
- :mod:`~gelly_tpu.obs.tracing` — a low-overhead per-unit
  :class:`SpanTracer`: every pipeline unit carries its id through
  produce → compress (worker K) → H2D (buffer slot) → fold →
  merge-window close → checkpoint, each span recording thread/worker,
  queue depth and payload sizes into a bounded ring buffer. Disabled
  (the default) the unit path performs ZERO extra allocations — every
  call site is guarded by a plain ``tracer is not None`` check on a
  generator-local binding.
- :mod:`~gelly_tpu.obs.export` — Chrome-trace-event JSON
  (Perfetto-loadable): one track per stage/worker, instant events for
  retries/faults/window closes, and the tracer's ``trace_id`` in
  ``otherData`` so a device-side ``jax.profiler`` trace captured around
  the same run (``utils.metrics.trace(log_dir, tracer=...)``) can be
  laid alongside it.
- :mod:`~gelly_tpu.obs.heartbeat` — a periodic progress line (eps,
  queue depths, last-retired position) for long streams.
"""

from .bus import EventBus, get_bus, scope
from .export import (
    to_chrome_trace,
    validate_chrome_trace,
    write_chrome_trace,
)
from .heartbeat import Heartbeat
from .tracing import SpanTracer, active_tracer, install

__all__ = [
    "EventBus",
    "get_bus",
    "scope",
    "SpanTracer",
    "active_tracer",
    "install",
    "to_chrome_trace",
    "validate_chrome_trace",
    "write_chrome_trace",
    "Heartbeat",
]
