"""Periodic progress heartbeat for long streams.

A multi-hour stream gives no sign of life between merge windows; the
heartbeat is the bounded, cheap answer: the executor calls
:meth:`Heartbeat.tick` once per retired unit, and at most once per
``every_s`` seconds the call actually emits — one structured line via
``logging`` (``gelly_tpu.obs`` INFO), a copy into :attr:`lines` (tests
and callers read it programmatically), and an instant event on the
active span tracer so exported traces show the beats on the timeline.

The line carries the fields ISSUE 5 names: edges/sec so far, the
pipeline queue depths (read from the bus gauges the prefetch legs
publish), and the last-retired chunk position (the exactly-once resume
point — what a crash right now would resume from). Every line also
carries HOST IDENTITY (``process_index`` / ``process_count`` /
``coordinator_address`` from ``parallel/mesh.host_info``, plus the
live ``leader`` flag when a coordinated-recovery ``Coordinator`` is
active) so interleaved multi-host logs and Perfetto captures are
attributable per host.
"""

from __future__ import annotations

import logging
import threading
import time

logger = logging.getLogger("gelly_tpu.obs")


def host_fields() -> dict:
    """Static host identity plus the live leadership flag — merged into
    every heartbeat line and into exported traces' ``otherData``.
    Lazy imports keep ``obs`` importable standalone; leadership comes
    from the active ``engine/coordination.Coordinator`` (absent → the
    ``leader`` key is omitted, single-host logs stay unchanged)."""
    from ..parallel.mesh import host_info

    fields = host_info()
    from ..engine.coordination import leader_flag

    leader = leader_flag()
    if leader is not None:
        fields["leader"] = leader
    return fields


class Heartbeat:
    """Rate-limited progress reporter. ``tick(**fields)`` is safe to
    call per unit: it is a clock read + compare except when a beat is
    due. ``every_s <= 0`` beats on every tick (tests)."""

    def __init__(self, every_s: float = 10.0, max_lines: int = 256,
                 clock=time.monotonic):
        from collections import deque

        self.every_s = every_s
        self._clock = clock
        self._last = clock()
        self._lock = threading.Lock()
        self.beats = 0
        self.lines: "deque[dict]" = deque(maxlen=max_lines)

    def due(self) -> bool:
        """Lock-free pre-check: callers on a hot path guard with this so
        the per-tick cost is ONE clock compare — building tick()'s field
        dict only when a beat will actually emit. Racy by design (tick
        re-checks under the lock); a false positive costs one discarded
        dict, never a duplicate beat."""
        return self._clock() - self._last >= self.every_s

    def tick(self, **fields) -> bool:
        """Maybe emit a beat; returns True when one was emitted."""
        now = self._clock()
        with self._lock:
            if now - self._last < self.every_s:
                return False
            self._last = now
            self.beats += 1
            # Captured INSIDE the lock: building the line from
            # self.beats after release let two threads that both won a
            # beat stamp the same number (every_s<=0, or ticks straddling
            # the cadence boundary) — lines must be attributable 1:1.
            beat_no = self.beats
        # Host identity rides every line (beats are rate-limited, so the
        # two lazy imports + leadership read cost nothing on the hot
        # path — tick() returns above long before this).
        line = dict(host_fields(), **fields, beat=beat_no)
        self.lines.append(line)
        logger.info(
            "heartbeat %s",
            " ".join(f"{k}={v}" for k, v in sorted(line.items())),
        )
        from .tracing import active_tracer

        tr = active_tracer()
        if tr is not None:
            tr.instant("heartbeat", **line)
        return True
