"""Chrome-trace-event JSON export (Perfetto-loadable).

The exported object is the Chrome Trace Event format's "JSON Object
Format" (the one Perfetto, ``chrome://tracing`` and ``ui.perfetto.dev``
all load):

    {"traceEvents": [...], "displayTimeUnit": "ms",
     "otherData": {"trace_id": ..., "counters": ..., "gauges": ...}}

Tracks: every distinct ``track`` string the tracer recorded (one per
stage/worker — ``compress/w140233…``, ``h2d/slot0``, ``fold``,
``merge_emit``, ``checkpoint``, ``events``) becomes one ``tid`` inside
``pid`` 1, named via ``"M"``-phase ``thread_name`` metadata events so
the viewer shows lanes by stage, not by raw thread id. Span timestamps
are converted from the tracer's seconds to the microseconds the format
requires; instant events carry ``"s": "g"`` (global scope) so they draw
as full-height markers.

Alignment with a device-side ``jax.profiler`` trace: both carry the
tracer's ``trace_id`` (``otherData.trace_id`` here; the profiler trace
directory is recorded under ``otherData.jax_profiler`` when
``utils.metrics.trace(log_dir, tracer=...)`` ran around the same run),
so the two timelines can be opened side by side and matched.

:func:`validate_chrome_trace` is the schema check the tests and the
bench artifact path share — load-bearing validation, not a smoke print.

Multi-host stitching: each host of a coordinated run exports its own
trace file (one ring per process; ``otherData.host`` carries the
``process_index`` identity). :func:`stitch_traces` merges them into a
single timeline — one ``pid`` per host, clocks aligned on the first
``coordination.barrier_agreed`` instant every host recorded (matched by
its ``epoch`` arg), and Perfetto flow arrows (``"s"``/``"f"`` phase
pairs sharing an ``id``) synthesized at every shared barrier so the
viewer draws the cross-host hand-off explicitly.
"""

from __future__ import annotations

import json
from typing import Any

from .tracing import SpanTracer

_US = 1e6  # tracer seconds -> trace-event microseconds

PID = 1


def to_chrome_trace(tracer: SpanTracer, bus=None,
                    extra: dict | None = None) -> dict:
    """Render ``tracer``'s ring (and optionally a bus snapshot) to a
    Chrome-trace dict. ``extra`` merges into ``otherData``."""
    records = tracer.records()
    # Stable track -> tid assignment in first-seen order.
    tids: dict[str, int] = {}
    events: list[dict] = [{
        "ph": "M", "name": "process_name", "pid": PID, "tid": 0,
        "args": {"name": f"gelly_tpu:{tracer.trace_id}"},
    }]
    for r in records:
        track = r["track"]
        if track not in tids:
            tids[track] = len(tids) + 1
            events.append({
                "ph": "M", "name": "thread_name", "pid": PID,
                "tid": tids[track], "args": {"name": track},
            })
    for r in records:
        ev: dict[str, Any] = {
            "name": r["name"], "ph": r["ph"], "cat": "gelly",
            "ts": round(r["ts"] * _US, 3),
            "pid": PID, "tid": tids[r["track"]],
            "args": dict(r["args"], thread=r["thread"]),
        }
        if r["ph"] == "X":
            ev["dur"] = round(r["dur"] * _US, 3)
        elif r["ph"] == "i":
            ev["s"] = "g"
        events.append(ev)
    from .heartbeat import host_fields

    other = {
        "trace_id": tracer.trace_id,
        "span_capacity": tracer.capacity,
        "spans_dropped": tracer.dropped,
        # Host identity (process_index/count, coordinator address,
        # leader flag): multi-host Perfetto captures — one trace file
        # per host — stay attributable after they leave the machine.
        "host": host_fields(),
    }
    if bus is not None:
        other.update(bus.snapshot())
    if extra:
        other.update(extra)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": other,
    }


def write_chrome_trace(path: str, tracer: SpanTracer, bus=None,
                       extra: dict | None = None) -> dict:
    """Validate + write the trace to ``path``; returns the trace dict."""
    trace = to_chrome_trace(tracer, bus=bus, extra=extra)
    validate_chrome_trace(trace)
    with open(path, "w") as f:
        json.dump(trace, f, indent=1)
        f.write("\n")
    return trace


def validate_chrome_trace(trace: dict) -> None:
    """Raise ``ValueError`` unless ``trace`` is well-formed Chrome-trace
    JSON (object format): JSON-serializable, ``traceEvents`` a list of
    events each carrying ``name``/``ph``/``pid``/``tid``, numeric ``ts``
    on non-metadata phases, numeric non-negative ``dur`` on ``"X"``
    spans, flow events (``"s"``/``"f"``) carrying an ``id`` (and
    ``"bp": "e"`` on the finish side), and every referenced
    ``(pid, tid)`` named by a ``thread_name`` metadata event."""
    if not isinstance(trace, dict):
        raise ValueError(f"trace must be a dict, got {type(trace).__name__}")
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        raise ValueError("trace['traceEvents'] must be a list")
    try:
        json.dumps(trace)
    except (TypeError, ValueError) as e:
        raise ValueError(f"trace is not JSON-serializable: {e}") from e
    named_tids = set()
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            raise ValueError(f"event #{i} is not a dict")
        for key in ("name", "ph", "pid", "tid"):
            if key not in ev:
                raise ValueError(f"event #{i} ({ev.get('name')}) lacks "
                                 f"required key {key!r}")
        ph = ev["ph"]
        if ph == "M":
            if ev["name"] == "thread_name":
                named_tids.add((ev["pid"], ev["tid"]))
            continue
        if not isinstance(ev.get("ts"), (int, float)):
            raise ValueError(f"event #{i} ({ev['name']}): ts must be numeric")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                raise ValueError(
                    f"event #{i} ({ev['name']}): 'X' span needs numeric "
                    f"dur >= 0, got {dur!r}")
        elif ph == "i":
            if ev.get("s") not in ("g", "p", "t"):
                raise ValueError(
                    f"event #{i} ({ev['name']}): instant needs scope "
                    "'s' in g/p/t")
        elif ph in ("s", "f"):
            if "id" not in ev:
                raise ValueError(
                    f"event #{i} ({ev['name']}): flow event needs an 'id'")
            if ph == "f" and ev.get("bp") != "e":
                raise ValueError(
                    f"event #{i} ({ev['name']}): flow finish needs "
                    "'bp': 'e' to bind at the enclosing slice")
        else:
            raise ValueError(f"event #{i}: unexpected phase {ph!r}")
        if ev["tid"] != 0 and (ev["pid"], ev["tid"]) not in named_tids:
            raise ValueError(
                f"event #{i} ({ev['name']}): tid {ev['tid']} has no "
                "thread_name metadata (track unnamed in the viewer)")


def _load_trace(t) -> dict:
    if isinstance(t, dict):
        return t
    with open(t) as f:
        return json.load(f)


def stitch_traces(traces, out_path: str | None = None,
                  barrier_name: str = "coordination.barrier_agreed") -> dict:
    """Merge per-host Chrome traces into one multi-process timeline.

    ``traces`` is a sequence of trace dicts or file paths (one per
    host, as written by :func:`write_chrome_trace`). Each host becomes
    its own ``pid`` (``process_index + 1``; enumeration order when a
    trace carries no host identity), keeping every per-host track lane
    intact. Host clocks are monotonic-from-different-epochs, so they
    are aligned on the first ``barrier_name`` instant **every** host
    recorded (matched by its ``epoch`` arg — the agreement instant is
    the one event all hosts log for the same logical moment); hosts
    missing a shared barrier merge unaligned with offset 0. At every
    shared barrier epoch a Perfetto flow arrow (``"s"`` on the
    reference host, ``"f"``/``"bp": "e"`` on each other host, shared
    ``id``) is synthesized so the cross-host hand-off draws explicitly.

    Validates the stitched trace, optionally writes it to
    ``out_path``, and returns it.
    """
    loaded = [_load_trace(t) for t in traces]
    if not loaded:
        raise ValueError("stitch_traces needs at least one trace")
    hosts: list[tuple[int, dict]] = []
    for i, tr in enumerate(loaded):
        other = tr.get("otherData") or {}
        hinfo = other.get("host") or {}
        idx = hinfo.get("process_index")
        hosts.append((idx if isinstance(idx, int) else i, tr))
    hosts.sort(key=lambda p: p[0])

    def _barriers(tr: dict) -> dict:
        out: dict = {}
        for ev in tr.get("traceEvents", []):
            if ev.get("ph") == "i" and ev.get("name") == barrier_name:
                ep = (ev.get("args") or {}).get("epoch")
                if ep is not None and ep not in out:
                    out[ep] = ev
        return out

    per_host = [_barriers(tr) for _, tr in hosts]
    common = set(per_host[0])
    for b in per_host[1:]:
        common &= set(b)
    # Align on the FIRST shared barrier: offsets shift every host's
    # timeline so that instant lands at the reference host's timestamp.
    offsets: list[float] = []
    for b in per_host:
        if common:
            ep0 = min(common)
            offsets.append(per_host[0][ep0]["ts"] - b[ep0]["ts"])
        else:
            offsets.append(0.0)

    events: list[dict] = []
    host_meta: dict[str, dict] = {}
    for (hidx, tr), off in zip(hosts, offsets):
        pid = hidx + 1
        other = tr.get("otherData") or {}
        host_meta[str(pid)] = {
            "trace_id": other.get("trace_id"),
            "host": other.get("host") or {},
            "clock_offset_us": round(off, 3),
        }
        events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": f"host{hidx}:{other.get('trace_id', '')}"},
        })
        for ev in tr.get("traceEvents", []):
            ev = dict(ev)
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                continue  # replaced by the per-host name above
            ev["pid"] = pid
            if ev.get("ph") != "M":
                ev["ts"] = round(ev["ts"] + off, 3)
            events.append(ev)

    ref_pid = hosts[0][0] + 1
    for ep in sorted(common):
        ref_ev = per_host[0][ep]
        fid = f"barrier-{ep}"
        events.append({
            "ph": "s", "name": "barrier_flow", "cat": "gelly", "id": fid,
            "ts": round(ref_ev["ts"] + offsets[0], 3),
            "pid": ref_pid, "tid": ref_ev["tid"],
        })
        for slot in range(1, len(hosts)):
            bev = per_host[slot][ep]
            events.append({
                "ph": "f", "bp": "e", "name": "barrier_flow",
                "cat": "gelly", "id": fid,
                "ts": round(bev["ts"] + offsets[slot], 3),
                "pid": hosts[slot][0] + 1, "tid": bev["tid"],
            })

    trace = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "stitched_hosts": len(hosts),
            "hosts": host_meta,
            "barrier_epochs": sorted(common),
        },
    }
    validate_chrome_trace(trace)
    if out_path is not None:
        with open(out_path, "w") as f:
            json.dump(trace, f, indent=1)
            f.write("\n")
    return trace
