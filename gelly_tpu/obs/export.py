"""Chrome-trace-event JSON export (Perfetto-loadable).

The exported object is the Chrome Trace Event format's "JSON Object
Format" (the one Perfetto, ``chrome://tracing`` and ``ui.perfetto.dev``
all load):

    {"traceEvents": [...], "displayTimeUnit": "ms",
     "otherData": {"trace_id": ..., "counters": ..., "gauges": ...}}

Tracks: every distinct ``track`` string the tracer recorded (one per
stage/worker — ``compress/w140233…``, ``h2d/slot0``, ``fold``,
``merge_emit``, ``checkpoint``, ``events``) becomes one ``tid`` inside
``pid`` 1, named via ``"M"``-phase ``thread_name`` metadata events so
the viewer shows lanes by stage, not by raw thread id. Span timestamps
are converted from the tracer's seconds to the microseconds the format
requires; instant events carry ``"s": "g"`` (global scope) so they draw
as full-height markers.

Alignment with a device-side ``jax.profiler`` trace: both carry the
tracer's ``trace_id`` (``otherData.trace_id`` here; the profiler trace
directory is recorded under ``otherData.jax_profiler`` when
``utils.metrics.trace(log_dir, tracer=...)`` ran around the same run),
so the two timelines can be opened side by side and matched.

:func:`validate_chrome_trace` is the schema check the tests and the
bench artifact path share — load-bearing validation, not a smoke print.
"""

from __future__ import annotations

import json
from typing import Any

from .tracing import SpanTracer

_US = 1e6  # tracer seconds -> trace-event microseconds

PID = 1


def to_chrome_trace(tracer: SpanTracer, bus=None,
                    extra: dict | None = None) -> dict:
    """Render ``tracer``'s ring (and optionally a bus snapshot) to a
    Chrome-trace dict. ``extra`` merges into ``otherData``."""
    records = tracer.records()
    # Stable track -> tid assignment in first-seen order.
    tids: dict[str, int] = {}
    events: list[dict] = [{
        "ph": "M", "name": "process_name", "pid": PID, "tid": 0,
        "args": {"name": f"gelly_tpu:{tracer.trace_id}"},
    }]
    for r in records:
        track = r["track"]
        if track not in tids:
            tids[track] = len(tids) + 1
            events.append({
                "ph": "M", "name": "thread_name", "pid": PID,
                "tid": tids[track], "args": {"name": track},
            })
    for r in records:
        ev: dict[str, Any] = {
            "name": r["name"], "ph": r["ph"], "cat": "gelly",
            "ts": round(r["ts"] * _US, 3),
            "pid": PID, "tid": tids[r["track"]],
            "args": dict(r["args"], thread=r["thread"]),
        }
        if r["ph"] == "X":
            ev["dur"] = round(r["dur"] * _US, 3)
        elif r["ph"] == "i":
            ev["s"] = "g"
        events.append(ev)
    from .heartbeat import host_fields

    other = {
        "trace_id": tracer.trace_id,
        "span_capacity": tracer.capacity,
        "spans_dropped": tracer.dropped,
        # Host identity (process_index/count, coordinator address,
        # leader flag): multi-host Perfetto captures — one trace file
        # per host — stay attributable after they leave the machine.
        "host": host_fields(),
    }
    if bus is not None:
        other.update(bus.snapshot())
    if extra:
        other.update(extra)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": other,
    }


def write_chrome_trace(path: str, tracer: SpanTracer, bus=None,
                       extra: dict | None = None) -> dict:
    """Validate + write the trace to ``path``; returns the trace dict."""
    trace = to_chrome_trace(tracer, bus=bus, extra=extra)
    validate_chrome_trace(trace)
    with open(path, "w") as f:
        json.dump(trace, f, indent=1)
        f.write("\n")
    return trace


def validate_chrome_trace(trace: dict) -> None:
    """Raise ``ValueError`` unless ``trace`` is well-formed Chrome-trace
    JSON (object format): JSON-serializable, ``traceEvents`` a list of
    events each carrying ``name``/``ph``/``pid``/``tid``, numeric ``ts``
    on non-metadata phases, numeric non-negative ``dur`` on ``"X"``
    spans, and every referenced ``tid`` named by a ``thread_name``
    metadata event."""
    if not isinstance(trace, dict):
        raise ValueError(f"trace must be a dict, got {type(trace).__name__}")
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        raise ValueError("trace['traceEvents'] must be a list")
    try:
        json.dumps(trace)
    except (TypeError, ValueError) as e:
        raise ValueError(f"trace is not JSON-serializable: {e}") from e
    named_tids = set()
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            raise ValueError(f"event #{i} is not a dict")
        for key in ("name", "ph", "pid", "tid"):
            if key not in ev:
                raise ValueError(f"event #{i} ({ev.get('name')}) lacks "
                                 f"required key {key!r}")
        ph = ev["ph"]
        if ph == "M":
            if ev["name"] == "thread_name":
                named_tids.add(ev["tid"])
            continue
        if not isinstance(ev.get("ts"), (int, float)):
            raise ValueError(f"event #{i} ({ev['name']}): ts must be numeric")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                raise ValueError(
                    f"event #{i} ({ev['name']}): 'X' span needs numeric "
                    f"dur >= 0, got {dur!r}")
        elif ph == "i":
            if ev.get("s") not in ("g", "p", "t"):
                raise ValueError(
                    f"event #{i} ({ev['name']}): instant needs scope "
                    "'s' in g/p/t")
        else:
            raise ValueError(f"event #{i}: unexpected phase {ph!r}")
        if ev["tid"] != 0 and ev["tid"] not in named_tids:
            raise ValueError(
                f"event #{i} ({ev['name']}): tid {ev['tid']} has no "
                "thread_name metadata (track unnamed in the viewer)")
