"""Per-unit span tracing with a bounded ring buffer.

A :class:`SpanTracer` records COMPLETE spans (recorded once, at span
end) and instant events into a ``collections.deque(maxlen=...)`` — a
bounded ring, so a long stream can trace forever and keep the newest
window. Records are plain dicts; timestamps are seconds on the tracer's
monotonic clock, zeroed at construction (the exporter converts to the
microseconds Chrome/Perfetto expect).

Overhead contract (ISSUE 5): tracer NOT installed ⇒ zero allocations on
the pipeline's unit path. The engine binds ``tracer = active_tracer()``
once per run and guards every site with ``if tracer is not None`` — no
span objects, no kwargs dicts, not even a clock read when disabled.
Installed ⇒ one dict + one deque append per span, measured <2% eps on
the ``streaming_cc_large`` capture (the ``obs`` block in bench.py
records tracer-on vs tracer-off each capture).

Threading: spans are recorded from compress workers, the H2D thread and
the consumer concurrently; ``deque.append`` is atomic under the GIL and
the record is fully built before the append, so no lock is needed on
the hot path.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Iterator


class SpanTracer:
    """Bounded-ring span recorder.

    - :meth:`now` — monotonic seconds since tracer start (span starts);
    - :meth:`span` — record a completed span: stage name, ``track``
      (the export lane, e.g. ``"compress/w3"``), start + now as the
      interval, plus arbitrary attribution fields (unit id, worker,
      queue depth, bytes/edges);
    - :meth:`instant` — a point event (retry, fault, window close);
    - :attr:`trace_id` — shared correlation id: stamp it into a
      ``jax.profiler`` device trace captured around the same run
      (``utils.metrics.trace(log_dir, tracer=...)`` does this) and the
      two timelines can be laid side by side in Perfetto.
    """

    def __init__(self, capacity: int = 1 << 16,
                 heartbeat_every_s: float | None = 10.0):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        from collections import deque

        self._ring: "deque[dict]" = deque(maxlen=capacity)
        self.capacity = capacity
        self.trace_id = os.urandom(8).hex()
        self._clock = time.perf_counter
        self.t0 = self._clock()
        # The engine starts a Heartbeat at this cadence when the tracer
        # is installed; None disables it.
        self.heartbeat_every_s = heartbeat_every_s
        self.dropped = 0  # ring evictions are counted, never silent
        self._drop_lock = threading.Lock()

    # ------------------------------------------------------------ hot path

    def now(self) -> float:
        return self._clock() - self.t0

    def span(self, stage: str, track: str, t0: float, **attrs) -> None:
        """Record ``[t0, now]`` as a completed span on ``track``."""
        t1 = self.now()
        if len(self._ring) == self.capacity:
            with self._drop_lock:
                self.dropped += 1
        self._ring.append({
            "ph": "X", "name": stage, "track": track,
            "ts": t0, "dur": max(0.0, t1 - t0),
            "tid": threading.get_ident(),
            "thread": threading.current_thread().name,
            "args": attrs,
        })

    def instant(self, name: str, track: str = "events", **attrs) -> None:
        if len(self._ring) == self.capacity:
            with self._drop_lock:
                self.dropped += 1
        self._ring.append({
            "ph": "i", "name": name, "track": track,
            "ts": self.now(),
            "tid": threading.get_ident(),
            "thread": threading.current_thread().name,
            "args": attrs,
        })

    # ------------------------------------------------------------- reading

    def records(self) -> list[dict]:
        """Snapshot of the ring, oldest → newest. (``list(deque)`` is a
        GIL-atomic copy; readers must go through it — a comprehension
        over the LIVE deque raises "deque mutated during iteration"
        when in-flight pipeline workers are still appending.)"""
        return list(self._ring)

    def spans(self, stage: str | None = None) -> list[dict]:
        return [r for r in self.records()
                if r["ph"] == "X" and (stage is None or r["name"] == stage)]

    def instants(self, name: str | None = None) -> list[dict]:
        return [r for r in self.records()
                if r["ph"] == "i" and (name is None or r["name"] == name)]


_ACTIVE: SpanTracer | None = None
_ACTIVE_LOCK = threading.Lock()


def active_tracer() -> SpanTracer | None:
    """The installed tracer, or None — THE disabled-path check: callers
    bind the result once and guard every record site with it."""
    return _ACTIVE


@contextlib.contextmanager
def install(tracer: SpanTracer) -> Iterator[SpanTracer]:
    """Activate ``tracer`` for the dynamic extent (same install shape as
    ``engine/faults.py``). Tracers do not nest — a second install inside
    an active one raises instead of silently splitting the timeline."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        if _ACTIVE is not None:
            raise RuntimeError("a SpanTracer is already installed")
        _ACTIVE = tracer
    try:
        yield tracer
    finally:
        with _ACTIVE_LOCK:
            _ACTIVE = None
