"""Per-unit span tracing with a bounded ring buffer.

A :class:`SpanTracer` records COMPLETE spans (recorded once, at span
end) and instant events into a ``collections.deque(maxlen=...)`` — a
bounded ring, so a long stream can trace forever and keep the newest
window. Records are plain dicts; timestamps are seconds on the tracer's
monotonic clock, zeroed at construction (the exporter converts to the
microseconds Chrome/Perfetto expect).

Overhead contract (ISSUE 5): tracer NOT installed ⇒ zero allocations on
the pipeline's unit path. The engine binds ``tracer = active_tracer()``
once per run and guards every site with ``if tracer is not None`` — no
span objects, no kwargs dicts, not even a clock read when disabled.
Installed ⇒ one dict + one deque append per span, measured <2% eps on
the ``streaming_cc_large`` capture (the ``obs`` block in bench.py
records tracer-on vs tracer-off each capture).

Threading: spans are recorded from compress workers, the H2D thread and
the consumer concurrently; ``deque.append`` is atomic under the GIL and
the record is fully built before the append, so no lock is needed on
the hot path.

**Wire trace propagation** (ISSUE 20): the tracer also owns the two
pieces the causal chain across the wire needs — a monotonic span-id
allocator (:meth:`SpanTracer.next_span_id`; ids are per-tracer, stamped
into span ``args`` as ``span=``/``parent=`` so an exported trace links
client-send → wire recv → staging → fold → checkpoint), and a BOUNDED
position→context registry (:meth:`bind_ctx` / :meth:`ctx`): the ingest
server binds each staged chunk position to its staging span's context,
and the engine's fold/checkpoint sites look the context up by position
to parent their spans on it. The registry is a plain dict plus an
insertion-order eviction deque capped at :data:`CTX_CAPACITY` entries —
a long stream cannot grow it, and an evicted position simply yields an
unlinked (but still recorded) span.

**Flight recorder** (rotating-segment mode): construct with
``SpanTracer(segment_s=K, segments=N)`` and the ring becomes a bounded
ring of N TIME segments — the newest ``N * K`` seconds of spans are
retained regardless of record rate (eviction is whole oldest segments,
counted in ``dropped``; ``capacity`` bounds records per segment as a
memory backstop). :meth:`dump` exports the retained window as a valid
Chrome trace at any moment, and :meth:`dump_on` subscribes to the event
bus so an INCIDENT — an injected fault, a watchdog timeout, a
degradation — automatically exports the spans surrounding it to a file,
after the fact, with no debugger attached. ``EventBus.emit`` records
the triggering instant into the tracer BEFORE the subscriber fan-out,
so every flight dump contains its own incident marker.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Iterator

# Bound on the position→trace-context registry (bind_ctx/ctx): oldest
# bindings evict first. 4096 positions is far past any staging queue +
# in-flight fold window, so a linked span only loses its parent when
# the pipeline is tens of thousands of chunks behind — at which point
# backlog, not trace linkage, is the story.
CTX_CAPACITY = 4096


class SpanTracer:
    """Bounded-ring span recorder.

    - :meth:`now` — monotonic seconds since tracer start (span starts);
    - :meth:`span` — record a completed span: stage name, ``track``
      (the export lane, e.g. ``"compress/w3"``), start + now as the
      interval, plus arbitrary attribution fields (unit id, worker,
      queue depth, bytes/edges);
    - :meth:`instant` — a point event (retry, fault, window close);
    - :attr:`trace_id` — shared correlation id: stamp it into a
      ``jax.profiler`` device trace captured around the same run
      (``utils.metrics.trace(log_dir, tracer=...)`` does this) and the
      two timelines can be laid side by side in Perfetto.
    """

    def __init__(self, capacity: int = 1 << 16,
                 heartbeat_every_s: float | None = 10.0,
                 segment_s: float | None = None, segments: int = 8,
                 clock=None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        from collections import deque

        self._ring: "deque[dict]" = deque(maxlen=capacity)
        self.capacity = capacity
        self.trace_id = os.urandom(8).hex()
        self._clock = clock if clock is not None else time.perf_counter
        self.t0 = self._clock()
        # The engine starts a Heartbeat at this cadence when the tracer
        # is installed; None disables it.
        self.heartbeat_every_s = heartbeat_every_s
        self.dropped = 0  # ring evictions are counted, never silent
        self._drop_lock = threading.Lock()
        # Flight-recorder (rotating-segment) mode: retain the newest
        # ``segments * segment_s`` seconds instead of the newest
        # ``capacity`` records. ``capacity`` stays as the per-segment
        # record bound (memory backstop against a record storm).
        if segment_s is not None and segment_s <= 0:
            raise ValueError(f"segment_s must be > 0, got {segment_s}")
        if segments < 2:
            raise ValueError(f"segments must be >= 2, got {segments}")
        self.segment_s = segment_s
        self.segments = segments
        self._seg_lock = threading.Lock()
        self._sealed: "deque[list]" = deque()
        self._cur: list = []
        self._seg_start = 0.0
        self.dumps: list = []  # flight-dump paths, newest last
        # Wire-propagation state: the span-id allocator (itertools.count
        # — next() on it is GIL-atomic, so concurrent stages allocate
        # without a lock) and the bounded position→context registry.
        import itertools

        self._span_ids = itertools.count(1)
        self._ctx: dict = {}
        self._ctx_order: "deque" = deque()
        self._ctx_lock = threading.Lock()

    # ------------------------------------------------------------ hot path

    def now(self) -> float:
        return self._clock() - self.t0

    def _append(self, rec: dict) -> None:
        if self.segment_s is None:
            if len(self._ring) == self.capacity:
                with self._drop_lock:
                    self.dropped += 1
            self._ring.append(rec)
            return
        ts = rec["ts"]
        if ts - self._seg_start >= self.segment_s:
            with self._seg_lock:
                if ts - self._seg_start >= self.segment_s:
                    # Seal the current segment; appenders that read the
                    # old list reference land their record in the sealed
                    # segment — retained either way.
                    self._sealed.append(self._cur)
                    self._cur = []
                    self._seg_start = ts
                    while len(self._sealed) > self.segments - 1:
                        old = self._sealed.popleft()
                        with self._drop_lock:
                            self.dropped += len(old)
        cur = self._cur
        if len(cur) >= self.capacity:
            with self._drop_lock:
                self.dropped += 1
            return
        cur.append(rec)

    def span(self, stage: str, track: str, t0: float, **attrs) -> None:
        """Record ``[t0, now]`` as a completed span on ``track``."""
        t1 = self.now()
        self._append({
            "ph": "X", "name": stage, "track": track,
            "ts": t0, "dur": max(0.0, t1 - t0),
            "tid": threading.get_ident(),
            "thread": threading.current_thread().name,
            "args": attrs,
        })

    def instant(self, name: str, track: str = "events", **attrs) -> None:
        self._append({
            "ph": "i", "name": name, "track": track,
            "ts": self.now(),
            "tid": threading.get_ident(),
            "thread": threading.current_thread().name,
            "args": attrs,
        })

    # ------------------------------------------------- wire trace context

    def next_span_id(self) -> int:
        """Allocate a span id for cross-span linkage (stamped into span
        ``args`` as ``span=``; children record it as ``parent=``). Ids
        are unique per tracer and never reused."""
        return next(self._span_ids)

    def bind_ctx(self, key, trace: str, span: int) -> None:
        """Bind ``key`` (a chunk position, or any hashable stage key)
        to a trace context ``(trace_id_hex, span_id)`` so a later stage
        that only knows the position can parent its span on it. The
        registry holds at most :data:`CTX_CAPACITY` bindings — oldest
        evict first, so a stalled consumer can never grow it."""
        with self._ctx_lock:
            if key not in self._ctx:
                self._ctx_order.append(key)
                while len(self._ctx_order) > CTX_CAPACITY:
                    self._ctx.pop(self._ctx_order.popleft(), None)
            self._ctx[key] = (trace, span)

    def ctx(self, key) -> tuple[str, int] | None:
        """The bound ``(trace_id_hex, span_id)`` for ``key``, or None
        (never bound, or evicted — the caller records an unlinked
        span)."""
        with self._ctx_lock:
            return self._ctx.get(key)

    # ------------------------------------------------------------- reading

    def records(self) -> list[dict]:
        """Snapshot of the ring, oldest → newest. (``list(deque)`` is a
        GIL-atomic copy; readers must go through it — a comprehension
        over the LIVE deque raises "deque mutated during iteration"
        when in-flight pipeline workers are still appending.)"""
        if self.segment_s is None:
            return list(self._ring)
        with self._seg_lock:
            out: list = []
            for seg in self._sealed:
                out.extend(seg)
            out.extend(self._cur)
            return out

    def spans(self, stage: str | None = None) -> list[dict]:
        return [r for r in self.records()
                if r["ph"] == "X" and (stage is None or r["name"] == stage)]

    def instants(self, name: str | None = None) -> list[dict]:
        return [r for r in self.records()
                if r["ph"] == "i" and (name is None or r["name"] == name)]

    # ------------------------------------------------------ flight recorder

    # The default incident set dump_on() wires when called without
    # event names: every injected fault, watchdog fire and
    # native->fallback degradation exports the surrounding spans.
    INCIDENT_EVENTS = ("faults.injected", "resilience.watchdog_timeouts",
                       "resilience.degradations")

    def dump(self, path: str, bus=None, extra: dict | None = None) -> dict:
        """Export the currently retained ring as a validated Chrome
        trace to ``path`` (works in both ring modes); returns the trace
        dict. This is the after-the-fact read: the last
        ``segments * segment_s`` seconds of spans around an incident,
        without a debugger attached."""
        from .export import write_chrome_trace

        return write_chrome_trace(path, self, bus=bus, extra=extra)

    def dump_on(self, *events: str, out_dir: str, bus=None,
                limit: int = 8):
        """Wire incident-triggered dumps: subscribe to ``bus`` (default:
        the current :func:`~gelly_tpu.obs.bus.get_bus`) and, whenever
        one of ``events`` (default :data:`INCIDENT_EVENTS` — injected
        faults, watchdog timeouts, degradations) is emitted, export the
        ring to ``out_dir/flight-<n>-<event>.json``. At most ``limit``
        dumps per wiring (an incident storm must not turn the recorder
        into a disk-filling incident of its own); paths land in
        :attr:`dumps` and each dump bumps the ``obs.flight_dumps``
        counter. Returns the unsubscribe callable."""
        from . import bus as bus_mod

        want = frozenset(events) if events else frozenset(
            self.INCIDENT_EVENTS)
        target_bus = bus if bus is not None else bus_mod.get_bus()
        state = {"n": 0}
        state_lock = threading.Lock()

        def on_incident(name: str, fields: dict) -> None:
            if name not in want:
                return
            with state_lock:
                if state["n"] >= limit:
                    return
                n = state["n"]
                state["n"] += 1
            path = os.path.join(
                out_dir, f"flight-{n:03d}-{name.replace('.', '_')}.json"
            )
            try:
                self.dump(path, bus=target_bus, extra={
                    "incident": name,
                    "incident_fields": {k: repr(v)
                                        for k, v in fields.items()},
                })
            except Exception:  # noqa: BLE001 — never fault the emitter
                import logging

                logging.getLogger("gelly_tpu.obs").exception(
                    "flight-recorder dump for %r failed", name)
                return
            self.dumps.append(path)
            # Count on the SUBSCRIBED bus: with an explicit ``bus=``
            # the current bus at dump time may be a different scope —
            # the counter must land next to the incident it counts.
            target_bus.inc("obs.flight_dumps")

        return target_bus.subscribe(on_incident)


_ACTIVE: SpanTracer | None = None
_ACTIVE_LOCK = threading.Lock()


def active_tracer() -> SpanTracer | None:
    """The installed tracer, or None — THE disabled-path check: callers
    bind the result once and guard every record site with it."""
    return _ACTIVE


@contextlib.contextmanager
def install(tracer: SpanTracer) -> Iterator[SpanTracer]:
    """Activate ``tracer`` for the dynamic extent (same install shape as
    ``engine/faults.py``). Tracers do not nest — a second install inside
    an active one raises instead of silently splitting the timeline."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        if _ACTIVE is not None:
            raise RuntimeError("a SpanTracer is already installed")
        _ACTIVE = tracer
    try:
        yield tracer
    finally:
        with _ACTIVE_LOCK:
            _ACTIVE = None
