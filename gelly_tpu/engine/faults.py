"""Deterministic fault injection for the resilient streaming runtime.

The reference inherits chaos testing from Flink's harness (TaskManager kills,
checkpoint barrier races); this repo re-owns the runtime, so it must also
re-own the ability to *drive* every failure path on demand. A
:class:`FaultPlan` is a seeded schedule of faults at named **boundaries** —
the places the resilient driver (``engine/resilience.py``) and the native
bindings (``utils/native.py``) call :func:`inject`:

- ``"native"``            — entry of a ctypes call into a native library
- ``"codec"``             — a codec worker staging a unit (host compress)
- ``"ingest"``            — the ingest subsystem's own boundaries
                            (``gelly_tpu/ingest/``): a sharded reader
                            lane about to parse a chunk, the server's
                            per-frame receive path, and the client's
                            send path — so the seeded fault matrix
                            drives reader and socket failures too
- ``"h2d"``               — host→device staging of a chunk
- ``"step"``              — the jitted ``step(state, chunk)`` dispatch
- ``"source"``            — the chunk source / prefetch worker
- ``"collective"``        — the cross-shard window-close merge (the
                            engine's butterfly/hierarchical/delta merge
                            dispatch in ``close_window``)
- ``"barrier"``           — the multi-host coordination protocol
                            (``engine/coordination.py``): fires with the
                            intent path inside ``agree_position``, at
                            ``publish`` entry, and with the manifest
                            path right after a commit — so
                            ``kind="corrupt"`` there models a torn
                            manifest
- ``"checkpoint_write"``  — before a checkpoint file write
- ``"checkpoint_read"``   — before a checkpoint file read
- ``"checkpoint_corrupt"``— after a checkpoint write, with the file path
                            (``kind="corrupt"`` mutates the file at
                            path-carrying boundaries to simulate a torn
                            write)

Faults fire by per-boundary call index, so a plan is reproducible
run-to-run regardless of thread interleaving at other boundaries; the only
randomness is the seeded ``rate`` mode. Nothing here is imported by the hot
path unless a plan is installed — :func:`inject` is a module-global
``None`` check when inactive.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import random
import threading
import time
from typing import Callable, Iterator, Sequence

BOUNDARIES = (
    "native",
    "codec",
    "ingest",
    "h2d",
    "step",
    "source",
    "collective",
    "barrier",
    "checkpoint_write",
    "checkpoint_read",
    "checkpoint_corrupt",
)

KINDS = ("raise", "hang", "corrupt")


class FaultInjected(RuntimeError):
    """Raised by a ``kind="raise"`` fault. ``retryable`` feeds the driver's
    error classification (a non-retryable injected fault models a permanent
    error, e.g. corrupt input data)."""

    def __init__(self, boundary: str, index: int, retryable: bool = True):
        super().__init__(
            f"injected fault at boundary '{boundary}' (call #{index})"
        )
        self.boundary = boundary
        self.index = index
        self.retryable = retryable


@dataclasses.dataclass
class Fault:
    """One scheduled fault.

    ``at`` — the per-boundary call index (0-based) at which to start firing;
    ``count`` consecutive calls fire. ``rate`` instead fires each call with
    that probability from the plan's seeded RNG (mutually exclusive with
    ``at``). ``exc`` overrides the raised exception (instance or zero-arg
    factory). ``kind="hang"`` sleeps ``hang_seconds`` (bounded, so an
    un-watchdogged test cannot wedge forever); ``kind="corrupt"`` truncates
    the file at the injection point's ``path`` to half its size — a torn
    write — and only fires at path-carrying boundaries.
    """

    boundary: str
    at: int | None = None
    kind: str = "raise"
    count: int = 1
    rate: float | None = None
    exc: BaseException | Callable[[], BaseException] | None = None
    hang_seconds: float = 30.0
    retryable: bool = True

    def __post_init__(self):
        if self.boundary not in BOUNDARIES:
            raise ValueError(
                f"unknown boundary {self.boundary!r}; expected one of "
                f"{BOUNDARIES}"
            )
        if self.kind not in KINDS:
            raise ValueError(f"unknown kind {self.kind!r}; expected {KINDS}")
        if (self.at is None) == (self.rate is None):
            raise ValueError("exactly one of at / rate must be set")


class FaultPlan:
    """A seeded, thread-safe schedule of :class:`Fault`s.

    Install with :func:`install` (context manager); every :func:`inject`
    call inside the block consults the plan. ``fired`` records
    ``(boundary, index, kind)`` tuples for test assertions.
    """

    def __init__(self, faults: Sequence[Fault], seed: int = 0):
        self.faults = list(faults)
        self._rng = random.Random(seed)
        self._counts: dict[str, int] = {}
        self._lock = threading.Lock()
        self.fired: list[tuple[str, int, str]] = []

    def _match(self, boundary: str, index: int) -> Fault | None:
        for f in self.faults:
            if f.boundary != boundary:
                continue
            if f.at is not None:
                if f.at <= index < f.at + f.count:
                    return f
            elif self._rng.random() < f.rate:
                return f
        return None

    def fire(self, boundary: str, path: str | None = None) -> None:
        with self._lock:
            index = self._counts.get(boundary, 0)
            self._counts[boundary] = index + 1
            f = self._match(boundary, index)
            if f is not None:
                self.fired.append((boundary, index, f.kind))
        if f is None:
            return
        # Published BEFORE the fault takes effect (outside the plan lock):
        # a hang or kill-adjacent raise still leaves the injection visible
        # on the obs bus — and, with a tracer installed, as an instant
        # event on the exported timeline (one per injected fault).
        from ..obs import bus as obs_bus

        obs_bus.get_bus().emit(
            "faults.injected", boundary=boundary, index=index, kind=f.kind,
        )
        if f.kind == "hang":
            time.sleep(f.hang_seconds)
            return
        if f.kind == "corrupt":
            if path is None:
                raise ValueError(
                    f"corrupt fault at boundary '{boundary}' needs a file "
                    "path; use a checkpoint_corrupt-style boundary"
                )
            _tear_file(path)
            return
        if f.exc is not None:
            raise f.exc() if callable(f.exc) else f.exc
        raise FaultInjected(boundary, index, retryable=f.retryable)

    def calls(self, boundary: str) -> int:
        with self._lock:
            return self._counts.get(boundary, 0)


def _tear_file(path: str) -> None:
    """Truncate ``path`` to half its size — a torn/partial write."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size // 2)


# ---------------------------------------------------------------------- #
# active-plan registry

_ACTIVE: FaultPlan | None = None
_ACTIVE_LOCK = threading.Lock()


def inject(boundary: str, path: str | None = None) -> None:
    """Fault hook — a no-op unless a plan is installed."""
    plan = _ACTIVE
    if plan is not None:
        plan.fire(boundary, path=path)


@contextlib.contextmanager
def install(plan: FaultPlan) -> Iterator[FaultPlan]:
    """Activate ``plan`` for the dynamic extent of the block.

    Also hooks the native bindings (``utils/native.py``) so ctypes entry
    points fire the ``"native"`` boundary without utils importing engine.
    Plans do not nest — a second install inside an active one raises.
    """
    global _ACTIVE
    from ..utils import native

    with _ACTIVE_LOCK:
        if _ACTIVE is not None:
            raise RuntimeError("a FaultPlan is already installed")
        _ACTIVE = plan
        native._fault_hook = lambda stem: plan.fire("native")
    try:
        yield plan
    finally:
        with _ACTIVE_LOCK:
            _ACTIVE = None
            native._fault_hook = None
