from .aggregation import (
    SummaryAggregation,
    SummaryStream,
    edges_fold_adapter,
    run_aggregation,
)
from .checkpoint import (
    CheckpointCorruptError,
    load_checkpoint,
    save_checkpoint,
)
from .coordination import (
    CheckpointStore,
    CoordinationConfig,
    CoordinationError,
    Coordinator,
    HostIdentity,
    ManifestCorruptError,
    MixedEpochError,
)
from .multiquery import (
    MultiQueryPlan,
    MultiQueryStream,
    QuerySpec,
    fuse,
    run_multiquery,
)
from .tenants import (
    MultiTenantEngine,
    TenantBatch,
)
from .resilience import (
    CheckpointManager,
    ResilienceConfig,
    ResilientRunner,
    RetriesExhausted,
    RetryPolicy,
    WatchdogTimeout,
    resilient_fold,
)
