from .aggregation import (
    SummaryAggregation,
    SummaryStream,
    edges_fold_adapter,
    run_aggregation,
)
from .checkpoint import load_checkpoint, save_checkpoint
