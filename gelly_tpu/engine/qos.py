"""Tenant QoS policy plane: weighted fair share, rate limits,
admission control and the shed/park degradation ladder.

PR 14 landed the *signals* (``tenants.backlog_age_max_s`` watermarks,
``tenants.round_ms`` histograms, live STATS); this module owns the
*policy* the reference delegates to Flink's runtime. It is pure host
bookkeeping — no JAX, no locks shared with the engine — so the
scheduler can consult it inside its own critical sections without lock
ordering concerns (the controller's lock is a leaf).

Three decisions, one declarative :class:`QosPolicy` per tenant (or the
tier-wide default):

- **Weighted fair scheduling** (:meth:`QosController.plan_round`):
  deficit-round-robin over policy weights. Each scheduling round every
  backlogged tenant accrues ``weight / max_weight`` credit (so the
  heaviest tenant accrues exactly 1 and dispatches every round); a
  tenant dispatches when its credit reaches 1 and its token bucket
  (``rate_limit_cps`` chunks/sec, ``burst`` deep) has a token.
  Fairness bound: over any R consecutive rounds a continuously
  backlogged, un-limited tenant with weight w_i receives at least
  ``floor(R * w_i / w_max) - 1`` chunks — deficit carries over, so no
  tenant is starved below its weight share.
- **Admission control**: :meth:`MultiTenantEngine.admit` consults
  ``admission_ceiling_s`` against the worst ACTIVE tenant backlog age
  and either refuses (:class:`AdmissionRefused`) or queues the
  admission until pressure drains (``admission="queue"``).
- **The degradation ladder** (:meth:`QosController.evaluate`): a
  tenant over its ``backlog_budget_s`` for ``limit_after`` consecutive
  evaluations is **limited** (weight scaled by
  ``limited_weight_factor``, rate capped at ``degraded_rate_cps``);
  still over for ``park_after`` more, it is **parked** (the engine
  frees its lane via the PR 12 reclamation machinery, snapshots keep
  answering, the wire holds the stream); a parked tenant whose queue
  keeps growing past ``shed_queue_depth`` is **shed** (stream closed
  with a typed NACK). Parked tenants un-park automatically once the
  ACTIVE pressure drains below ``unpark_below_s`` — re-entering at
  the *limited* rung with a ``unpark_grace_s`` escalation holiday, so
  their own (necessarily stale) backlog cannot instantly re-park them.

Every transition is returned to the engine as an action string; the
engine publishes it on the bus (``qos.*`` counters/gauges — see the
``obs.bus`` glossary) and fires its ``on_qos`` hooks (the ingest
router maps park/unpark/shed onto wire PAUSE/RESUME/NACK).
"""

from __future__ import annotations

import dataclasses
import threading
import time

__all__ = [
    "AdmissionRefused",
    "QOS_LIMITED",
    "QOS_OK",
    "QOS_PARKED",
    "QOS_SHED",
    "QosController",
    "QosPolicy",
]

# Ladder states, mildest first. String-valued on purpose: they ride
# telemetry()/STATS/heartbeat payloads as-is.
QOS_OK = "ok"
QOS_LIMITED = "limited"
QOS_PARKED = "parked"
QOS_SHED = "shed"


class AdmissionRefused(RuntimeError):
    """``admit()`` refused a tenant: the engine is over its admission
    ceiling (``admission="refuse"``). Carries the pressure reading so
    callers can back off informedly."""

    def __init__(self, tenant_id, backlog_age_s: float, ceiling_s: float):
        super().__init__(
            f"tenant {tenant_id!r} refused admission: active backlog "
            f"age {backlog_age_s:.3f}s exceeds the admission ceiling "
            f"{ceiling_s:.3f}s — drain or shed before admitting more "
            "load (or construct the QosController with "
            "admission='queue')"
        )
        self.tenant_id = tenant_id
        self.backlog_age_s = backlog_age_s
        self.ceiling_s = ceiling_s


@dataclasses.dataclass(frozen=True)
class QosPolicy:
    """Declarative per-tenant (or tier-default) QoS contract.

    ``weight`` — fair-share weight (chunks per round relative to the
    heaviest tenant). ``rate_limit_cps`` — token-bucket rate in
    chunks/sec (None = unlimited), ``burst`` tokens deep.
    ``backlog_budget_s`` — the degradation trigger: ingress→durable
    backlog age above it counts an over-budget evaluation (None = the
    ladder never engages). ``limit_after`` / ``park_after`` —
    consecutive over-budget evaluations before the limit / park rungs.
    ``limited_weight_factor`` / ``degraded_rate_cps`` — the limited
    rung's effective weight multiplier and rate cap.
    ``unpark_below_s`` — un-park once ACTIVE pressure drains below
    this (default: half the budget); the same threshold clears the
    limited rung. ``unpark_grace_s`` — escalation holiday after an
    un-park. ``shed_queue_depth`` — a PARKED tenant whose queue grows
    past this is shed (None = never shed).
    """

    weight: float = 1.0
    rate_limit_cps: float | None = None
    backlog_budget_s: float | None = None
    limit_after: int = 1
    park_after: int = 3
    limited_weight_factor: float = 0.25
    degraded_rate_cps: float | None = None
    unpark_below_s: float | None = None
    unpark_grace_s: float = 0.5
    shed_queue_depth: int | None = None
    burst: float = 2.0

    def __post_init__(self):
        if not (self.weight > 0):
            raise ValueError(f"weight must be > 0, got {self.weight}")
        for name in ("rate_limit_cps", "degraded_rate_cps",
                     "backlog_budget_s", "unpark_below_s"):
            v = getattr(self, name)
            if v is not None and not (v > 0):
                raise ValueError(f"{name} must be > 0, got {v}")
        if self.limit_after < 1 or self.park_after < 1:
            raise ValueError(
                "limit_after and park_after must be >= 1 evaluations, "
                f"got {self.limit_after} / {self.park_after}"
            )
        if not (0 < self.limited_weight_factor <= 1):
            raise ValueError(
                "limited_weight_factor must be in (0, 1], got "
                f"{self.limited_weight_factor}"
            )
        if self.shed_queue_depth is not None and self.shed_queue_depth < 1:
            raise ValueError(
                f"shed_queue_depth must be >= 1, got "
                f"{self.shed_queue_depth}"
            )
        if not (self.burst >= 1):
            raise ValueError(f"burst must be >= 1 token, got {self.burst}")
        if self.unpark_grace_s < 0:
            raise ValueError(
                f"unpark_grace_s must be >= 0, got {self.unpark_grace_s}"
            )

    def unpark_threshold(self) -> float | None:
        """The drain level that un-parks / clears the limit: explicit
        ``unpark_below_s``, else half the backlog budget."""
        if self.unpark_below_s is not None:
            return self.unpark_below_s
        if self.backlog_budget_s is not None:
            return self.backlog_budget_s / 2.0
        return None


class _TenantQos:
    """Controller-private per-tenant scheduling state."""

    __slots__ = ("credit", "tokens", "t_tokens", "over_evals", "state",
                 "grace_until")

    def __init__(self, now: float, burst: float):
        self.credit = 0.0
        self.tokens = burst  # start with a full bucket
        self.t_tokens = now
        self.over_evals = 0
        self.state = QOS_OK
        self.grace_until = 0.0


class QosController:
    """The policy engine: per-tenant DRR credit, token buckets and
    ladder state. Thread-safe behind one leaf lock; the engine calls
    :meth:`plan_round` from its scheduling round and :meth:`evaluate`
    from its (rate-limited) QoS pass.

    ``default`` — the policy tenants fall back to; ``per_tenant`` —
    overrides keyed by tenant id (mutable later via
    :meth:`set_policy`). ``admission_ceiling_s`` + ``admission``
    ("refuse" | "queue") configure :meth:`MultiTenantEngine.admit`'s
    gate; ``eval_every_s`` paces the engine's ladder evaluations.
    ``clock`` is injectable for deterministic tests.
    """

    def __init__(self, default: QosPolicy | None = None,
                 per_tenant: dict | None = None, *,
                 admission_ceiling_s: float | None = None,
                 admission: str = "refuse",
                 eval_every_s: float = 0.05,
                 clock=time.monotonic):
        if admission not in ("refuse", "queue"):
            raise ValueError(
                f"admission must be 'refuse' or 'queue', got {admission!r}"
            )
        if admission_ceiling_s is not None and not (admission_ceiling_s > 0):
            raise ValueError(
                f"admission_ceiling_s must be > 0, got "
                f"{admission_ceiling_s}"
            )
        self.default = default if default is not None else QosPolicy()
        self.admission_ceiling_s = admission_ceiling_s
        self.admission = admission
        self.eval_every_s = float(eval_every_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._policies: dict = dict(per_tenant or {})
        self._state: dict = {}

    # ------------------------------------------------------------ policies

    def policy_for(self, tenant_id) -> QosPolicy:
        with self._lock:
            return self._policies.get(tenant_id, self.default)

    def set_policy(self, tenant_id, policy: QosPolicy) -> None:
        """Install/replace one tenant's policy (takes effect on the
        next round/evaluation — no state reset: ladder position and
        accrued credit survive a policy tweak)."""
        if not isinstance(policy, QosPolicy):
            raise TypeError(f"expected QosPolicy, got {type(policy).__name__}")
        with self._lock:
            self._policies[tenant_id] = policy

    def state(self, tenant_id) -> str:
        """The tenant's ladder state (``ok`` for never-seen ids)."""
        with self._lock:
            st = self._state.get(tenant_id)
            return st.state if st is not None else QOS_OK

    def states(self) -> dict:
        """``{tenant_id: ladder state}`` for every tracked tenant."""
        with self._lock:
            return {tid: st.state for tid, st in self._state.items()}

    def counts(self) -> dict:
        """Ladder-state histogram — the ``qos.*_tenants`` gauges."""
        out = {QOS_OK: 0, QOS_LIMITED: 0, QOS_PARKED: 0, QOS_SHED: 0}
        with self._lock:
            for st in self._state.values():
                out[st.state] += 1
        return out

    def forget(self, tenant_id) -> None:
        """Drop a tenant's scheduling state (eviction cleanup)."""
        with self._lock:
            self._state.pop(tenant_id, None)

    def _st(self, tenant_id, pol: QosPolicy, now: float) -> _TenantQos:
        st = self._state.get(tenant_id)
        if st is None:
            st = self._state[tenant_id] = _TenantQos(now, pol.burst)
        return st

    # ----------------------------------------------------------- scheduling

    def plan_round(self, tenant_ids, now: float | None = None) -> set:
        """Deficit-round-robin grant set for one scheduling round.

        ``tenant_ids`` are the BACKLOGGED tenants (a chunk is queued);
        returns the subset granted a dispatch this round. Credit
        accrues at ``weight / max_weight`` per round (capped at one
        round's surplus, so an idle spell cannot bank unbounded burst)
        and a grant costs 1; the token bucket additionally gates
        limited/rate-capped tenants. Parked/shed tenants are never
        granted.
        """
        now = self._clock() if now is None else now
        granted: list = []
        with self._lock:
            entries: list = []
            wmax = 0.0
            for tid in tenant_ids:
                pol = self._policies.get(tid, self.default)
                st = self._st(tid, pol, now)
                if st.state in (QOS_PARKED, QOS_SHED):
                    continue
                w = pol.weight
                if st.state == QOS_LIMITED:
                    w *= pol.limited_weight_factor
                entries.append((tid, w, pol, st))
                wmax = max(wmax, w)
            if not entries:
                return set()
            for tid, w, pol, st in entries:
                quantum = w / wmax
                st.credit = min(st.credit + quantum, 1.0 + quantum)
                if st.credit < 1.0:
                    continue
                rate = pol.rate_limit_cps
                if st.state == QOS_LIMITED and pol.degraded_rate_cps is not None:
                    rate = (pol.degraded_rate_cps if rate is None
                            else min(rate, pol.degraded_rate_cps))
                if rate is not None:
                    st.tokens = min(
                        pol.burst,
                        st.tokens + (now - st.t_tokens) * rate,
                    )
                    st.t_tokens = now
                    if st.tokens < 1.0:
                        continue
                    st.tokens -= 1.0
                st.credit -= 1.0
                granted.append(tid)
        return set(granted)

    # ------------------------------------------------------------- ladder

    def evaluate(self, tenant_id, *, backlog_age_s: float,
                 queue_depth: int, active_backlog_max_s: float,
                 now: float | None = None) -> str | None:
        """Advance one tenant's ladder state; returns the transition
        ("limit" / "clear" / "park" / "unpark" / "shed") or None.

        ``backlog_age_s`` is the tenant's own ingress→durable age,
        ``queue_depth`` its engine queue, ``active_backlog_max_s`` the
        worst age across ACTIVE (un-parked) tenants — the un-park /
        admission pressure signal (a parked tenant's own ledger ages by
        construction and must not gate its own release)."""
        now = self._clock() if now is None else now
        with self._lock:
            pol = self._policies.get(tenant_id, self.default)
            st = self._st(tenant_id, pol, now)
            if st.state == QOS_SHED:
                return None
            if st.state == QOS_PARKED:
                if (pol.shed_queue_depth is not None
                        and queue_depth > pol.shed_queue_depth):
                    st.state = QOS_SHED
                    return "shed"
                thr = pol.unpark_threshold()
                if thr is not None and active_backlog_max_s < thr:
                    # Re-enter at the LIMITED rung with a grace
                    # holiday: the tenant's own backlog is stale from
                    # the park and must drain before full fair share.
                    st.state = QOS_LIMITED
                    st.over_evals = 0
                    st.grace_until = now + pol.unpark_grace_s
                    return "unpark"
                return None
            budget = pol.backlog_budget_s
            if budget is None:
                return None
            if backlog_age_s <= budget:
                st.over_evals = 0
                thr = pol.unpark_threshold()
                if (st.state == QOS_LIMITED
                        and backlog_age_s < (budget if thr is None else thr)):
                    st.state = QOS_OK
                    return "clear"
                return None
            if now < st.grace_until:
                return None  # un-park holiday: no escalation yet
            st.over_evals += 1
            if st.state == QOS_OK and st.over_evals >= pol.limit_after:
                st.state = QOS_LIMITED
                st.over_evals = 0
                return "limit"
            if st.state == QOS_LIMITED and st.over_evals >= pol.park_after:
                st.state = QOS_PARKED
                st.over_evals = 0
                return "park"
            return None
