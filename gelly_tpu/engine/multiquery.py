"""Fused multi-query execution: one shared ingest pipeline, N questions.

The r05 capture shows the wall is ingest, not compute: host compress
5.36s + H2D 2.51s against a 0.0009s fold dispatch on
``streaming_cc_large``. Every additional aggregation folded over the
*same* edge stream is therefore nearly free — if it shares the
produce/compress/H2D leg instead of re-running it. That sharing is the
reference's own execution model (one ``SimpleEdgeStream``, many
summaries: CC, degrees, bipartiteness, spanner — PAPER.md §L1), and the
natural serving shape for "millions of users asking different questions
of one traffic stream".

:func:`fuse` composes Q heterogeneous :class:`~gelly_tpu.engine.
aggregation.SummaryAggregation` plans into ONE
:class:`MultiQueryPlan` — itself a ``SummaryAggregation`` whose summary
is a dict of per-query summaries (plus a fold-step counter leaf). The
fused fold applies every query's fold to the SAME chunk inside one
compiled program, so the whole engine carries it unchanged:

- **Pipelined executor**: each chunk is produced, staged and
  transferred H2D exactly once; the fold dispatch count per chunk is 1
  regardless of Q (``run_aggregation(queries=[...])`` or
  ``stream.aggregate(None, queries=[...])``).
- **Per-query merge windows**: a non-accumulating query (e.g. the
  spanner, whose cross-window merge is the reference's
  ``CombineSpanners``) carries ``{local, global}`` sub-state and its
  merge runs INSIDE the fused fold as a masked no-op sub-fold — the
  same ``jnp.where``-select machinery as the tenant engine's masked
  lanes — firing only when the query's own ``QuerySpec.every`` window
  closes. Accumulating queries (CC forests, degree vectors, parity
  forests) carry one running summary, exactly like their standalone
  accumulate plans.
- **Checkpointing**: the fused state is one pytree, so the engine's
  existing exactly-once machinery snapshots every query's leaves in
  ONE rotation at ONE position (the last-retired-chunk rule; the step
  counter rides the same snapshot, so masked merge windows resume
  bit-identically — ``tests/_multiquery_crash_child.py`` proves it
  under SIGKILL).
- **Live snapshots** (:class:`MultiQueryStream`): per-query reads off
  the last closed window's emission dict, staleness bounded by one
  merge window, lock held only for the reference swap.
- **Multi-tenant tiers**: a ``MultiQueryPlan`` is a valid tier plan for
  :class:`~gelly_tpu.engine.tenants.MultiTenantEngine` — N tenants
  × Q queries ride one vmapped donated dispatch.

Fusion eligibility (refused loudly at :func:`fuse` time):

- plans folding only through a stateful host codec
  (``requires_codec`` / ``stack_ordered``) — their per-run id sessions
  cannot ride a shared raw-chunk fold;
- ``transient`` plans — their emit-and-reset window contract needs the
  engine's Merger path, which the fused accumulate plan bypasses;
- host-side transforms (``jit_transform=False``) — fused emissions are
  one jitted dict program;
- mismatched chunk schemas: queries declaring different
  ``slot_capacity`` read the same shared chunk, and a query built for
  a smaller slot space would silently mis-index it (JAX clamps);
- per-query ``every`` > 1 on an accumulating plan (no merge window to
  defer) and duplicate / reserved query names.

Per-query codecs (``host_compress``) are deliberately NOT engaged: the
fused pipeline stages each chunk once for every query, so the fused
fold is the RAW fold composition — build sub-plans with
``ingest_combine=False`` (the library ``*_query`` helpers do).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import bus as obs_bus
from ..obs import tracing as obs_tracing
from .aggregation import SummaryAggregation, SummaryStream

# Reserved leaf: the fused fold's chunk counter (drives the masked
# per-query merge windows; rides the checkpoint like any other leaf).
STEP_KEY = "_step"


class QuerySpec(NamedTuple):
    """One query riding the fused plan.

    - ``name`` — key of this query's summary/emission in the fused
      dicts (unique per plan; ``"_step"`` is reserved).
    - ``agg`` — the query's ``SummaryAggregation`` (raw fold; see the
      module eligibility rules).
    - ``every`` — merge-window cadence in CHUNKS for non-accumulating
      plans: the query's ``combine(local, global)`` sub-fold fires on
      every ``every``-th fused fold and is a masked no-op otherwise.
      Must be 1 for accumulating plans (nothing to defer).
    - ``slot_capacity`` — optional declared vertex slot-space size;
      ``fuse`` refuses to mix differing declared capacities (the
      queries read the same shared chunk).
    """

    name: str
    agg: SummaryAggregation
    every: int = 1
    slot_capacity: int | None = None

    @property
    def accum(self) -> bool:
        return self.agg.fold_accumulates and not self.agg.transient


@dataclasses.dataclass(eq=False)
class MultiQueryPlan(SummaryAggregation):
    """The fused plan: a ``SummaryAggregation`` over the dict-of-
    summaries state, built by :func:`fuse`. ``queries`` holds the
    normalized :class:`QuerySpec` tuple; everything else is the
    standard plugin contract, so the engine (and the tenant engine)
    need no new physical plan."""

    queries: tuple = ()

    @property
    def query_names(self) -> tuple:
        return tuple(q.name for q in self.queries)


def _as_spec(q) -> QuerySpec:
    if isinstance(q, QuerySpec):
        return q
    if isinstance(q, SummaryAggregation):
        return QuerySpec(name=q.name, agg=q)
    if isinstance(q, tuple) and len(q) == 2:
        return QuerySpec(name=q[0], agg=q[1])
    raise ValueError(
        f"cannot fuse {type(q).__name__}: pass a QuerySpec, a "
        "SummaryAggregation, or a (name, aggregation) pair"
    )


def fuse(queries, *, name: str | None = None) -> MultiQueryPlan:
    """Stack Q heterogeneous aggregations into one fused plan.

    ``queries`` — iterable of :class:`QuerySpec` /
    ``SummaryAggregation`` / ``(name, aggregation)`` pairs. Returns a
    :class:`MultiQueryPlan` whose fold advances EVERY query from the
    same chunk in one compiled program; run it through
    ``run_aggregation(queries=...)`` (which wraps the emission stream
    in a :class:`MultiQueryStream`) or hand it to
    ``MultiTenantEngine.add_tier`` as a tier plan.
    """
    specs = [_as_spec(q) for q in queries]
    if not specs:
        raise ValueError("fuse needs at least one query")
    seen: set = set()
    caps: dict = {}
    for q in specs:
        if not isinstance(q.agg, SummaryAggregation):
            raise ValueError(
                f"query {q.name!r}: agg must be a SummaryAggregation, "
                f"got {type(q.agg).__name__}"
            )
        if isinstance(q.agg, MultiQueryPlan):
            raise ValueError(
                f"query {q.name!r} is already a fused MultiQueryPlan — "
                "pass its sub-queries instead of nesting fusions"
            )
        if not q.name or q.name == STEP_KEY:
            raise ValueError(
                f"query name {q.name!r} is empty or reserved "
                f"({STEP_KEY!r} is the fused step-counter leaf)"
            )
        if q.name in seen:
            raise ValueError(f"duplicate query name {q.name!r}")
        seen.add(q.name)
        if q.agg.requires_codec or q.agg.stack_ordered:
            raise ValueError(
                f"query {q.name!r} ({q.agg.name}) folds through a "
                "stateful host codec (requires_codec/stack_ordered); "
                "the fused plan folds the shared RAW chunk — build the "
                "query without the ordered codec (e.g. "
                "ingest_combine=False)"
            )
        if q.agg.transient:
            raise ValueError(
                f"query {q.name!r} ({q.agg.name}) is transient "
                "(emit-and-reset windows); the fused accumulate plan "
                "has no per-window Merger to reset through — un-fusable"
            )
        if q.agg.transform is not None and not q.agg.jit_transform:
            raise ValueError(
                f"query {q.name!r} ({q.agg.name}) uses a host-side "
                "transform (jit_transform=False); fused emissions are "
                "one jitted dict program — un-fusable"
            )
        if not isinstance(q.every, int) or q.every < 1:
            raise ValueError(
                f"query {q.name!r}: every must be an int >= 1, got "
                f"{q.every!r}"
            )
        if q.accum and q.every != 1:
            raise ValueError(
                f"query {q.name!r} ({q.agg.name}) accumulates "
                "(fold_accumulates); it has no merge window to defer — "
                "every must be 1"
            )
        if q.slot_capacity is not None:
            caps[q.name] = int(q.slot_capacity)
    if len(set(caps.values())) > 1:
        raise ValueError(
            "mismatched chunk schemas: fused queries read the SAME "
            "shared chunk but declare different slot capacities "
            f"({caps}) — a query built for a smaller slot space would "
            "silently mis-index it (JAX clamps out-of-range ids)"
        )
    specs = tuple(specs)
    plan_name = name or "multiquery(" + "+".join(q.name for q in specs) + ")"

    def init():
        st: dict = {STEP_KEY: jnp.zeros((), jnp.int64)}
        for q in specs:
            if q.accum:
                st[q.name] = q.agg.init()
            else:
                st[q.name] = {"local": q.agg.init(),
                              "global": q.agg.init()}
        return st

    def fold(state, chunk):
        step = state[STEP_KEY] + 1
        out: dict = {STEP_KEY: step}
        for q in specs:
            if q.accum:
                out[q.name] = q.agg.fold(state[q.name], chunk)
                continue
            sub = state[q.name]
            local = q.agg.fold(sub["local"], chunk)
            # The per-query merge window as a masked no-op sub-fold
            # (the tenant engine's masked-lane machinery): the merge
            # is computed every chunk but SELECTED in only when this
            # query's own window closes — one program, no host
            # branching, vmap-safe under a tenant tier.
            boundary = (step % q.every) == 0
            merged = q.agg.combine(local, sub["global"])
            fresh = q.agg.init()
            out[q.name] = {
                "local": jax.tree.map(
                    lambda f, l: jnp.where(boundary, f, l), fresh, local
                ),
                "global": jax.tree.map(
                    lambda m, g: jnp.where(boundary, m, g),
                    merged, sub["global"],
                ),
            }
        return out

    def combine(a, b):
        # Cross-partition merge of fused states (per-query combine,
        # component-wise over the local/global sub-states). Sound for
        # accumulating sub-queries only — which is exactly the shape
        # run_aggregation admits at S > 1 (non-accum queries are
        # refused there: their in-fold merges are per-partition).
        out: dict = {STEP_KEY: jnp.maximum(a[STEP_KEY], b[STEP_KEY])}
        for q in specs:
            if q.accum:
                out[q.name] = q.agg.combine(a[q.name], b[q.name])
            else:
                out[q.name] = {
                    "local": q.agg.combine(a[q.name]["local"],
                                           b[q.name]["local"]),
                    "global": q.agg.combine(a[q.name]["global"],
                                            b[q.name]["global"]),
                }
        return out

    def transform(state):
        out: dict = {}
        for q in specs:
            if q.accum:
                view = state[q.name]
            else:
                # Merge-on-read: the emission always includes the
                # un-merged window tail, matching the standalone
                # plan's close-at-emission semantics (at a boundary,
                # local is freshly reset and combine(init, g) == g by
                # the Merger identity contract).
                view = q.agg.combine(state[q.name]["local"],
                                     state[q.name]["global"])
            if q.agg.transform is not None:
                out[q.name] = q.agg.transform(view)
            else:
                out[q.name] = view
        return out

    fused_flatten = None
    if any(q.agg.flatten is not None for q in specs):
        def fused_flatten(state):
            out: dict = {STEP_KEY: state[STEP_KEY]}
            for q in specs:
                f = q.agg.flatten
                if q.accum:
                    out[q.name] = (f(state[q.name]) if f is not None
                                   else state[q.name])
                elif f is not None:
                    out[q.name] = {
                        "local": f(state[q.name]["local"]),
                        "global": f(state[q.name]["global"]),
                    }
                else:
                    out[q.name] = state[q.name]
            return out

    return MultiQueryPlan(
        init=init,
        fold=fold,
        combine=combine,
        transform=transform,
        flatten=fused_flatten,
        # The fused plan presents as ONE accumulating summary: per-query
        # windowing (for non-accum sub-queries) happens inside the fold,
        # so the engine's single-running-state physical plan carries
        # every query with zero per-window Merger work of its own.
        fold_accumulates=True,
        transient=False,
        jit_transform=True,
        # An accumulating sub-query without a transform passes its live
        # state leaves through the fused emission — the engine must not
        # donate buffers an emission may still alias (the same rule as
        # its transform-less accumulate plan).
        transform_may_alias=any(
            q.accum and q.agg.transform is None for q in specs
        ),
        fold_backend="fused",
        merge_mode="replicated",
        name=plan_name,
        queries=specs,
    )


class MultiQueryStream(SummaryStream):
    """Emission stream of a fused run + live per-query snapshot reads.

    Iterating yields the fused emission dict (``{query_name:
    emission}``) once per closed merge window, exactly like
    ``SummaryStream``. While a consumer drives the stream,
    :meth:`snapshot` answers per-query reads from the LAST yielded
    window — staleness bounded by one merge window — from any thread;
    the lock is held only for the reference swap, never for D2H.
    """

    def __init__(self, inner: SummaryStream, plan: MultiQueryPlan):
        self._inner = inner
        self.plan = plan
        self._lock = threading.Lock()
        self._latest = None
        self._window = 0
        super().__init__(self._gen)
        self.stats = getattr(inner, "stats", None)
        self.timer = getattr(inner, "timer", None)

    def _gen(self):
        bus = obs_bus.get_bus()
        tracer = obs_tracing.active_tracer()
        names = self.plan.query_names
        bus.gauge("multiquery.fused_queries", len(names))
        bus.inc("multiquery.runs")
        it = iter(self._inner)
        while True:
            t0 = tracer.now() if tracer is not None else 0.0
            try:
                out = next(it)
            except StopIteration:
                return
            with self._lock:
                self._latest = out
                self._window += 1
                w = self._window
            bus.inc("multiquery.emissions", len(names))
            if tracer is not None:
                # Per-query attribution: one span per query per window
                # on its own multiquery/<name> track, covering the
                # window's wall — the exported trace shows the single
                # compress/H2D/fold pipeline feeding Q query tracks.
                for n in names:
                    tracer.span("multiquery", f"multiquery/{n}", t0,
                                query=n, window=w)
            yield out

    def snapshot(self, query: str | None = None):
        """Host copy of the named query's last-window emission (or the
        whole ``{name: emission}`` dict with ``query=None``). Returns
        ``None`` before the first window close."""
        with self._lock:
            latest = self._latest
        if latest is None:
            return None
        obs_bus.get_bus().inc("multiquery.snapshot_reads")
        if query is None:
            return {n: jax.tree.map(np.asarray, latest[n])
                    for n in self.plan.query_names}
        if query not in latest:
            raise ValueError(
                f"unknown query {query!r} (fused: "
                f"{list(self.plan.query_names)})"
            )
        return jax.tree.map(np.asarray, latest[query])

    def snapshot_window(self) -> int:
        """Merge-window number :meth:`snapshot` currently answers from
        (0 = none closed yet) — the staleness handle."""
        with self._lock:
            return self._window


def run_multiquery(queries, stream, **runner_kw) -> MultiQueryStream:
    """Convenience front end: ``run_aggregation(None, stream,
    queries=queries, **runner_kw)`` — one shared ingest pipeline, every
    query answered per chunk."""
    from .aggregation import run_aggregation

    return run_aggregation(None, stream, queries=queries, **runner_kw)
