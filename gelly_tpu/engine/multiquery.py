"""Fused multi-query execution: one shared ingest pipeline, N questions.

The r05 capture shows the wall is ingest, not compute: host compress
5.36s + H2D 2.51s against a 0.0009s fold dispatch on
``streaming_cc_large``. Every additional aggregation folded over the
*same* edge stream is therefore nearly free — if it shares the
produce/compress/H2D leg instead of re-running it. That sharing is the
reference's own execution model (one ``SimpleEdgeStream``, many
summaries: CC, degrees, bipartiteness, spanner — PAPER.md §L1), and the
natural serving shape for "millions of users asking different questions
of one traffic stream".

:func:`fuse` composes Q heterogeneous :class:`~gelly_tpu.engine.
aggregation.SummaryAggregation` plans into ONE
:class:`MultiQueryPlan` — itself a ``SummaryAggregation`` whose summary
is a dict of per-query summaries (plus a fold-step counter leaf). The
fused fold applies every query's fold to the SAME chunk inside one
compiled program, so the whole engine carries it unchanged:

- **Pipelined executor**: each chunk is produced, staged and
  transferred H2D exactly once; the fold dispatch count per chunk is 1
  regardless of Q (``run_aggregation(queries=[...])`` or
  ``stream.aggregate(None, queries=[...])``).
- **Per-query merge windows**: a non-accumulating query (e.g. the
  spanner, whose cross-window merge is the reference's
  ``CombineSpanners``) carries ``{local, global}`` sub-state and its
  merge runs INSIDE the fused fold as a masked no-op sub-fold — the
  same ``jnp.where``-select machinery as the tenant engine's masked
  lanes — firing only when the query's own ``QuerySpec.every`` window
  closes. Accumulating queries (CC forests, degree vectors, parity
  forests) carry one running summary, exactly like their standalone
  accumulate plans.
- **Checkpointing**: the fused state is one pytree, so the engine's
  existing exactly-once machinery snapshots every query's leaves in
  ONE rotation at ONE position (the last-retired-chunk rule; the step
  counter rides the same snapshot, so masked merge windows resume
  bit-identically — ``tests/_multiquery_crash_child.py`` proves it
  under SIGKILL).
- **Live snapshots** (:class:`MultiQueryStream`): per-query reads off
  the last closed window's emission dict, staleness bounded by one
  merge window, lock held only for the reference swap.
- **Multi-tenant tiers**: a ``MultiQueryPlan`` is a valid tier plan for
  :class:`~gelly_tpu.engine.tenants.MultiTenantEngine` — N tenants
  × Q queries ride one vmapped donated dispatch.

Fusion eligibility (refused loudly at :func:`fuse` time):

- plans whose codec is a STATEFUL ordered stacker (``stack_ordered``:
  the compact-id session consumes payloads in global stream order,
  which the shared per-chunk compress stage cannot provide);
- ``requires_codec`` plans in a set whose shared codec cannot engage
  (see below) — their raw fold does not exist;
- ``transient`` plans — their emit-and-reset window contract needs the
  engine's Merger path, which the fused accumulate plan bypasses;
- host-side transforms (``jit_transform=False``) — fused emissions are
  one jitted dict program;
- mismatched chunk schemas: queries declaring different
  ``slot_capacity`` read the same shared chunk, and a query built for
  a smaller slot space would silently mis-index it (JAX clamps);
- per-query ``every`` > 1 on an accumulating plan (no merge window to
  defer) and duplicate / reserved query names.

**Fused codec sharing** (the shared compression plane): when EVERY
query supplies a stateless ingest codec (``host_compress`` +
``fold_compressed``) and accumulates, the fused plan grows its own
shared compress stage — ONE multi-query compressed payload per chunk
(``{query_name: per-query payload}``), staged and transferred H2D
once, with every query's ``fold_compressed`` running inside the one
fused dispatch. The ~0.25 B/edge codec wire win then covers fused
runs too. Build the sub-queries with their codecs on (the library
``*_query`` helpers' ``compressed=True``); mixed or non-accumulating
sets fall back to the raw-chunk fused fold (per-query masked merge
windows need the per-chunk raw fold — a K-stacked payload dispatch
cannot interleave fold and merge at chunk grain). ``share_codec``
forces the decision: ``True`` refuses sets the codec cannot cover,
``False`` pins the raw path, ``"auto"`` (default) engages when
eligible.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import bus as obs_bus
from ..obs import tracing as obs_tracing
from .aggregation import SummaryAggregation, SummaryStream

# Reserved leaf: the fused fold's chunk counter (drives the masked
# per-query merge windows; rides the checkpoint like any other leaf).
STEP_KEY = "_step"


class QuerySpec(NamedTuple):
    """One query riding the fused plan.

    - ``name`` — key of this query's summary/emission in the fused
      dicts (unique per plan; ``"_step"`` is reserved).
    - ``agg`` — the query's ``SummaryAggregation`` (raw fold; see the
      module eligibility rules).
    - ``every`` — merge-window cadence in CHUNKS for non-accumulating
      plans: the query's ``combine(local, global)`` sub-fold fires on
      every ``every``-th fused fold and is a masked no-op otherwise.
      Must be 1 for accumulating plans (nothing to defer).
    - ``slot_capacity`` — optional declared vertex slot-space size;
      ``fuse`` refuses to mix differing declared capacities (the
      queries read the same shared chunk).
    """

    name: str
    agg: SummaryAggregation
    every: int = 1
    slot_capacity: int | None = None

    @property
    def accum(self) -> bool:
        return self.agg.fold_accumulates and not self.agg.transient


@dataclasses.dataclass(eq=False)
class MultiQueryPlan(SummaryAggregation):
    """The fused plan: a ``SummaryAggregation`` over the dict-of-
    summaries state, built by :func:`fuse`. ``queries`` holds the
    normalized :class:`QuerySpec` tuple; everything else is the
    standard plugin contract, so the engine (and the tenant engine)
    need no new physical plan."""

    queries: tuple = ()

    @property
    def query_names(self) -> tuple:
        return tuple(q.name for q in self.queries)


def _as_spec(q) -> QuerySpec:
    if isinstance(q, QuerySpec):
        return q
    if isinstance(q, SummaryAggregation):
        return QuerySpec(name=q.name, agg=q)
    if isinstance(q, tuple) and len(q) == 2:
        return QuerySpec(name=q[0], agg=q[1])
    raise ValueError(
        f"cannot fuse {type(q).__name__}: pass a QuerySpec, a "
        "SummaryAggregation, or a (name, aggregation) pair"
    )


def fuse(queries, *, name: str | None = None,
         share_codec="auto") -> MultiQueryPlan:
    """Stack Q heterogeneous aggregations into one fused plan.

    ``queries`` — iterable of :class:`QuerySpec` /
    ``SummaryAggregation`` / ``(name, aggregation)`` pairs. Returns a
    :class:`MultiQueryPlan` whose fold advances EVERY query from the
    same chunk in one compiled program; run it through
    ``run_aggregation(queries=...)`` (which wraps the emission stream
    in a :class:`MultiQueryStream`) or hand it to
    ``MultiTenantEngine.add_tier`` as a tier plan.

    ``share_codec`` — the fused-codec knob (module docs): ``"auto"``
    engages the shared compress stage when every query is
    codec-capable and accumulating, ``True`` REQUIRES it (ValueError
    otherwise), ``False`` pins the raw-chunk fused fold.
    """
    # Identity checks, not membership: 1 == True under `in`, but the
    # strictness branches below test `is True` / `is False` — an int
    # must not silently demote to "auto" semantics.
    if not (share_codec is True or share_codec is False
            or share_codec == "auto"):
        raise ValueError(
            f"share_codec must be 'auto', True or False, got "
            f"{share_codec!r}"
        )
    specs = [_as_spec(q) for q in queries]
    if not specs:
        raise ValueError("fuse needs at least one query")
    seen: set = set()
    caps: dict = {}
    for q in specs:
        if not isinstance(q.agg, SummaryAggregation):
            raise ValueError(
                f"query {q.name!r}: agg must be a SummaryAggregation, "
                f"got {type(q.agg).__name__}"
            )
        if isinstance(q.agg, MultiQueryPlan):
            raise ValueError(
                f"query {q.name!r} is already a fused MultiQueryPlan — "
                "pass its sub-queries instead of nesting fusions"
            )
        if not q.name or q.name == STEP_KEY:
            raise ValueError(
                f"query name {q.name!r} is empty or reserved "
                f"({STEP_KEY!r} is the fused step-counter leaf)"
            )
        if q.name in seen:
            raise ValueError(f"duplicate query name {q.name!r}")
        seen.add(q.name)
        if q.agg.stack_ordered:
            raise ValueError(
                f"query {q.name!r} ({q.agg.name}) uses an ordered "
                "stacker (stack_ordered: its codec session assigns "
                "compact ids in GLOBAL STREAM order); the fused "
                "shared-compress stage compresses every query from the "
                "same chunk with no cross-query ordering to offer — "
                "build the query on a stateless codec (codec='sparse') "
                "or the raw fold (ingest_combine=False)"
            )
        if q.agg.transient:
            raise ValueError(
                f"query {q.name!r} ({q.agg.name}) is transient "
                "(emit-and-reset windows); the fused accumulate plan "
                "has no per-window Merger to reset through — un-fusable"
            )
        windowed_panes = getattr(q.agg, "windowed_panes", None)
        if windowed_panes is not None:
            raise ValueError(
                f"query {q.name!r} ({q.agg.name}) carries a pane ring "
                f"(windowed_panes={windowed_panes}): the ring's "
                "two-stack suffix aggregation and TTL session rebuilds "
                "are single-stream host structures the shared fused "
                "fold cannot mask per query — run the windowed query "
                "as its own stream (windowed= on run_aggregation)"
            )
        if q.agg.transform is not None and not q.agg.jit_transform:
            raise ValueError(
                f"query {q.name!r} ({q.agg.name}) uses a host-side "
                "transform (jit_transform=False); fused emissions are "
                "one jitted dict program — un-fusable"
            )
        if not isinstance(q.every, int) or q.every < 1:
            raise ValueError(
                f"query {q.name!r}: every must be an int >= 1, got "
                f"{q.every!r}"
            )
        if q.accum and q.every != 1:
            raise ValueError(
                f"query {q.name!r} ({q.agg.name}) accumulates "
                "(fold_accumulates); it has no merge window to defer — "
                "every must be 1"
            )
        if q.slot_capacity is not None:
            caps[q.name] = int(q.slot_capacity)
    if len(set(caps.values())) > 1:
        raise ValueError(
            "mismatched chunk schemas: fused queries read the SAME "
            "shared chunk but declare different slot capacities "
            f"({caps}) — a query built for a smaller slot space would "
            "silently mis-index it (JAX clamps out-of-range ids)"
        )
    specs = tuple(specs)
    plan_name = name or "multiquery(" + "+".join(q.name for q in specs) + ")"

    # Fused codec sharing: engages only when EVERY query supplies a
    # stateless codec AND accumulates — a non-accumulating query's
    # masked merge window fires at CHUNK grain inside the raw fold,
    # which a K-stacked payload dispatch cannot interleave with.
    codec_capable = [
        q for q in specs
        if q.agg.host_compress is not None
        and q.agg.fold_compressed is not None
    ]
    codec_ok = len(codec_capable) == len(specs) and all(
        q.accum for q in specs
    )
    use_codec = codec_ok and share_codec in ("auto", True)
    if share_codec is True and not codec_ok:
        raise ValueError(
            "share_codec=True but the shared compress stage cannot "
            "cover this set: every query must supply host_compress + "
            "fold_compressed AND accumulate (codec-capable: "
            f"{[q.name for q in codec_capable]} of "
            f"{[q.name for q in specs]}; non-accumulating: "
            f"{[q.name for q in specs if not q.accum]}) — build the "
            "sub-queries with compressed=True, or drop share_codec"
        )
    codec_only = [q.name for q in specs if q.agg.requires_codec]
    if codec_only and not use_codec:
        raise ValueError(
            f"queries {codec_only} fold ONLY through their ingest "
            "codec (requires_codec) but the fused shared-compress "
            "stage is not engaged here"
            + (" (share_codec=False pins the raw path)"
               if share_codec is False else
               ": every fused query must be codec-capable and "
               "accumulating for it to engage")
            + " — their raw fold does not exist, so the set is "
            "un-fusable as-is"
        )

    def init():
        st: dict = {STEP_KEY: jnp.zeros((), jnp.int64)}
        for q in specs:
            if q.accum:
                st[q.name] = q.agg.init()
            else:
                st[q.name] = {"local": q.agg.init(),
                              "global": q.agg.init()}
        return st

    def fold(state, chunk):
        step = state[STEP_KEY] + 1
        out: dict = {STEP_KEY: step}
        for q in specs:
            if q.accum:
                out[q.name] = q.agg.fold(state[q.name], chunk)
                continue
            sub = state[q.name]
            local = q.agg.fold(sub["local"], chunk)
            # The per-query merge window as a masked no-op sub-fold
            # (the tenant engine's masked-lane machinery): the merge
            # is computed every chunk but SELECTED in only when this
            # query's own window closes — one program, no host
            # branching, vmap-safe under a tenant tier.
            boundary = (step % q.every) == 0
            merged = q.agg.combine(local, sub["global"])
            fresh = q.agg.init()
            out[q.name] = {
                "local": jax.tree.map(
                    lambda f, l: jnp.where(boundary, f, l), fresh, local
                ),
                "global": jax.tree.map(
                    lambda m, g: jnp.where(boundary, m, g),
                    merged, sub["global"],
                ),
            }
        return out

    def combine(a, b):
        # Cross-partition merge of fused states (per-query combine,
        # component-wise over the local/global sub-states). Sound for
        # accumulating sub-queries only — which is exactly the shape
        # run_aggregation admits at S > 1 (non-accum queries are
        # refused there: their in-fold merges are per-partition).
        out: dict = {STEP_KEY: jnp.maximum(a[STEP_KEY], b[STEP_KEY])}
        for q in specs:
            if q.accum:
                out[q.name] = q.agg.combine(a[q.name], b[q.name])
            else:
                out[q.name] = {
                    "local": q.agg.combine(a[q.name]["local"],
                                           b[q.name]["local"]),
                    "global": q.agg.combine(a[q.name]["global"],
                                            b[q.name]["global"]),
                }
        return out

    def transform(state):
        out: dict = {}
        for q in specs:
            if q.accum:
                view = state[q.name]
            else:
                # Merge-on-read: the emission always includes the
                # un-merged window tail, matching the standalone
                # plan's close-at-emission semantics (at a boundary,
                # local is freshly reset and combine(init, g) == g by
                # the Merger identity contract).
                view = q.agg.combine(state[q.name]["local"],
                                     state[q.name]["global"])
            if q.agg.transform is not None:
                out[q.name] = q.agg.transform(view)
            else:
                out[q.name] = view
        return out

    fused_host_compress = None
    fused_stack_payloads = None
    fused_fold_compressed = None
    fused_payload_check = None
    if use_codec:
        def fused_host_compress(chunk):
            # ONE multi-query compressed payload per chunk: each query's
            # own codec reduces the SAME chunk, and the dict rides the
            # pipeline as one unit — one staging pass, one H2D, one
            # fused dispatch. (The engine's empty identity chunk is not
            # a stream chunk; keep the counter honest.)
            if bool(np.any(np.asarray(chunk.valid))):
                obs_bus.get_bus().inc("multiquery.compressed_chunks")
            return {q.name: q.agg.host_compress(chunk) for q in specs}

        def fused_stack_payloads(payloads: list, groups: int = 1) -> dict:
            out: dict = {}
            for q in specs:
                subs = [p[q.name] for p in payloads]
                if q.agg.stack_payloads is not None:
                    out[q.name] = q.agg.stack_payloads(subs, groups)
                else:
                    out[q.name] = jax.tree.map(
                        lambda *ls: np.stack(
                            [np.asarray(x) for x in ls]
                        ),
                        *subs,
                    )
            return out

        def fused_payload_check(payload):
            # Producer-payload validation fans out per query (each
            # codec knows its own id ranges); a payload compressed by
            # a DIFFERENT fused set is named here, not at a KeyError
            # inside the fold.
            missing = [q.name for q in specs
                       if not isinstance(payload, dict)
                       or q.name not in payload]
            if missing:
                raise ValueError(
                    f"fused compressed payload is missing per-query "
                    f"sub-payloads {missing} — was it compressed by a "
                    "different fused plan?"
                )
            for q in specs:
                fn = q.agg.codec_payload_check
                if fn is not None:
                    fn(payload[q.name])

        def fused_fold_compressed(state, payload):
            # One dispatch folds a stacked unit of payload-chunks
            # ([K, ...] leaves, K static under jit). The step counter
            # is INERT on the codec path (all-accumulating by the
            # eligibility rule — no masked merge windows key off it);
            # it advances by the unit's widest per-query batch so it
            # stays monotone and unit-aligned. It is NOT numerically
            # equal to the raw twin's chunk count when every query's
            # stacker group-combines (fold_batch > 1) or on a sharded
            # mesh — the engine's checkpoint POSITION, not this leaf,
            # is the exactly-once authority either way.
            k = max(
                jax.tree.leaves(payload[q.name])[0].shape[0]
                for q in specs
            )
            out = {STEP_KEY: state[STEP_KEY] + k}
            for q in specs:
                out[q.name] = q.agg.fold_compressed(
                    state[q.name], payload[q.name]
                )
            return out

    fused_flatten = None
    if any(q.agg.flatten is not None for q in specs):
        def fused_flatten(state):
            out: dict = {STEP_KEY: state[STEP_KEY]}
            for q in specs:
                f = q.agg.flatten
                if q.accum:
                    out[q.name] = (f(state[q.name]) if f is not None
                                   else state[q.name])
                elif f is not None:
                    out[q.name] = {
                        "local": f(state[q.name]["local"]),
                        "global": f(state[q.name]["global"]),
                    }
                else:
                    out[q.name] = state[q.name]
            return out

    return MultiQueryPlan(
        init=init,
        fold=fold,
        combine=combine,
        transform=transform,
        flatten=fused_flatten,
        host_compress=fused_host_compress,
        fold_compressed=fused_fold_compressed,
        stack_payloads=fused_stack_payloads,
        codec_payload_check=fused_payload_check,
        # With the shared codec engaged, a codec-only sub-query makes
        # the WHOLE fused plan codec-only: the engine must refuse a
        # configuration where the codec cannot engage (mesh-unaligned
        # batch) instead of falling into the raw fold that would raise
        # mid-stream.
        requires_codec=use_codec and bool(codec_only),
        # The fused plan presents as ONE accumulating summary: per-query
        # windowing (for non-accum sub-queries) happens inside the fold,
        # so the engine's single-running-state physical plan carries
        # every query with zero per-window Merger work of its own.
        fold_accumulates=True,
        transient=False,
        jit_transform=True,
        # An accumulating sub-query without a transform passes its live
        # state leaves through the fused emission — the engine must not
        # donate buffers an emission may still alias (the same rule as
        # its transform-less accumulate plan).
        transform_may_alias=any(
            q.accum and q.agg.transform is None for q in specs
        ),
        fold_backend="fused",
        merge_mode="replicated",
        name=plan_name,
        queries=specs,
    )


class MultiQueryStream(SummaryStream):
    """Emission stream of a fused run + live per-query snapshot reads.

    Iterating yields the fused emission dict (``{query_name:
    emission}``) once per closed merge window, exactly like
    ``SummaryStream``. While a consumer drives the stream,
    :meth:`snapshot` answers per-query reads from the LAST yielded
    window — staleness bounded by one merge window — from any thread;
    the lock is held only for the reference swap, never for D2H.
    """

    def __init__(self, inner: SummaryStream, plan: MultiQueryPlan):
        self._inner = inner
        self.plan = plan
        self._lock = threading.Lock()
        self._latest = None
        self._window = 0
        super().__init__(self._gen)
        self.stats = getattr(inner, "stats", None)
        self.timer = getattr(inner, "timer", None)

    def _gen(self):
        bus = obs_bus.get_bus()
        tracer = obs_tracing.active_tracer()
        # Serving-plane telemetry guard, bound once per run (the same
        # discipline as the executor's): multiquery.emit_ms measures
        # the emission SNAPSHOT PUBLICATION — lock wait + reference
        # swap, the latency live snapshot() readers can induce on the
        # stream (the window's compute wall is engine.merge_emit_ms,
        # recorded inside the inner executor).
        telemetry = obs_bus.telemetry_on()
        names = self.plan.query_names
        bus.gauge("multiquery.fused_queries", len(names))
        bus.inc("multiquery.runs")
        it = iter(self._inner)
        import time as _time

        while True:
            t0 = tracer.now() if tracer is not None else 0.0
            try:
                out = next(it)
            except StopIteration:
                return
            t_h = _time.perf_counter() if telemetry else 0.0
            with self._lock:
                self._latest = out
                self._window += 1
                w = self._window
            if telemetry:
                bus.observe("multiquery.emit_ms",
                            (_time.perf_counter() - t_h) * 1e3)
            bus.inc("multiquery.emissions", len(names))
            if tracer is not None:
                # Per-query attribution: one span per query per window
                # on its own multiquery/<name> track, covering the
                # window's wall — the exported trace shows the single
                # compress/H2D/fold pipeline feeding Q query tracks.
                for n in names:
                    tracer.span("multiquery", f"multiquery/{n}", t0,
                                query=n, window=w)
            yield out

    def snapshot(self, query: str | None = None):
        """Host copy of the named query's last-window emission (or the
        whole ``{name: emission}`` dict with ``query=None``). Returns
        ``None`` before the first window close."""
        with self._lock:
            latest = self._latest
        if latest is None:
            return None
        obs_bus.get_bus().inc("multiquery.snapshot_reads")
        if query is None:
            return {n: jax.tree.map(np.asarray, latest[n])
                    for n in self.plan.query_names}
        if query not in latest:
            raise ValueError(
                f"unknown query {query!r} (fused: "
                f"{list(self.plan.query_names)})"
            )
        return jax.tree.map(np.asarray, latest[query])

    def snapshot_window(self) -> int:
        """Merge-window number :meth:`snapshot` currently answers from
        (0 = none closed yet) — the staleness handle."""
        with self._lock:
            return self._window


def run_multiquery(queries, stream, **runner_kw) -> MultiQueryStream:
    """Convenience front end: ``run_aggregation(None, stream,
    queries=queries, **runner_kw)`` — one shared ingest pipeline, every
    query answered per chunk."""
    from .aggregation import run_aggregation

    return run_aggregation(None, stream, queries=queries, **runner_kw)
