"""Resilient streaming driver: checkpointed folds, retry, crash recovery.

The reference delegates every one of these responsibilities to Flink
(``ListCheckpointed`` snapshot/restore, task restarts, backpressure); this
module re-owns them natively for the ``step(state, chunk) -> (state,
emission)`` fold contract shared by ``core/stream.py``,
``engine/aggregation.py`` and ``parallel/sharded_cc.py``:

- **Checkpointing woven into the loop** (:class:`CheckpointManager`):
  every N chunks and/or T seconds the device state is snapshotted to host
  and written on a background thread as ``ckpt-<position>.npz`` with
  per-leaf CRC32 + schema versioning (``engine/checkpoint.py`` v2),
  keep-last-K rotation. A torn or corrupt newest file is detected at load
  and the previous one used.
- **Exactly-once resume** (:meth:`ResilientRunner.run`): on restart the
  newest *valid* checkpoint is reloaded, the chunk source fast-forwarded to
  the recorded position (``iter_from``/``chunks_from`` seek when the source
  supports it, island skip otherwise), and the fold continues — a resumed
  run produces a bit-identical final state to an uninterrupted run.
  Emissions for already-folded chunks are not replayed (state is
  exactly-once; the emission side-channel is at-most-once across a crash).
- **Bounded retry with exponential backoff + jitter** (:class:`RetryPolicy`)
  and a **watchdog timeout** (:class:`Watchdog`) around the three fragile
  boundaries: native ctypes calls (classified by ``utils/native.py``),
  H2D staging / step dispatch, and checkpoint I/O. A hung call raises
  :class:`WatchdogTimeout` on the driver thread (the stuck daemon worker is
  abandoned) and is retried like any transient error; a hung or
  retry-exhausted CHECKPOINT write degrades instead — the fold continues
  with durability reduced, aborting only after
  ``max_checkpoint_failures`` consecutive misses (the end-of-stream
  checkpoint always surfaces its error).
- **Graceful degradation**: when a native library keeps erroring mid-stream
  the driver disables it process-wide (``native.disable``) and switches to
  the caller-supplied ``fallback_step`` (the numpy path), re-attempting the
  same chunk — state is functional, so the failed attempt left nothing
  behind.

Every path above is *driven* in tests by the deterministic fault harness in
``engine/faults.py`` (``pytest -m faults``), including a kill -9 crash test.

Every runtime decision above is also PUBLISHED, not just logged: retries,
watchdog fires, degradations, source restarts, checkpoint completions /
misses / bytes / latency all land on the process-wide ``obs`` event bus
(``gelly_tpu.obs.get_bus()``) as counters+events — tests and bench assert
on them programmatically, and an installed ``obs.SpanTracer`` shows each
as an instant event on the exported timeline.
"""

from __future__ import annotations

import dataclasses
import glob
import logging
import os
import random
import threading
import time
from typing import Any, Callable, Iterator

import jax
import numpy as np

from ..obs import bus as obs_bus
from ..utils import native as native_mod
from ..utils.prefetch import restartable_prefetch
from . import faults as faults_mod
from .checkpoint import (
    CheckpointCorruptError,
    load_checkpoint,
    save_checkpoint,
)
from .coordination import CoordinationError

logger = logging.getLogger("gelly_tpu.resilience")


class StreamFault(RuntimeError):
    """Base class for driver-level failures (always actionable text)."""


class RetriesExhausted(StreamFault):
    """A fragile boundary failed every attempt of its retry budget."""

    def __init__(self, boundary: str, attempts: int, last: BaseException):
        super().__init__(
            f"boundary '{boundary}' failed after {attempts} attempts; "
            f"last error: {type(last).__name__}: {last}"
        )
        self.boundary = boundary
        self.attempts = attempts


class WatchdogTimeout(TimeoutError):
    """A guarded call exceeded the watchdog timeout (treated as transient)."""

    def __init__(self, boundary: str, timeout: float):
        super().__init__(
            f"boundary '{boundary}' exceeded the {timeout:.3g}s watchdog "
            "timeout (hung native call / device transfer?)"
        )
        self.boundary = boundary


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with jitter: attempt k (0-based retry) sleeps
    ``min(base * multiplier**k, max_delay) * (1 + jitter * U[0,1))``.

    ``max_attempts`` counts total tries (first call + retries). Jitter uses
    the driver's seeded RNG, so schedules are reproducible in tests.
    """

    max_attempts: int = 4
    base_delay: float = 0.05
    multiplier: float = 2.0
    max_delay: float = 5.0
    jitter: float = 0.5

    def delay(self, retry_index: int, rng: random.Random) -> float:
        d = min(self.base_delay * self.multiplier ** retry_index,
                self.max_delay)
        return d * (1.0 + self.jitter * rng.random())


def default_retryable(exc: BaseException) -> bool:
    """Is this error worth retrying? Transient: watchdog timeouts, I/O and
    allocation failures, connection drops, retryable injected faults, and
    anything ``utils/native.py`` classifies as transient. Data-dependent
    errors (ValueError slot range, TypeError) are permanent — retrying
    replays the same failure."""
    if isinstance(exc, WatchdogTimeout):
        return True
    if isinstance(exc, faults_mod.FaultInjected):
        return exc.retryable
    if isinstance(exc, FileNotFoundError):
        return False
    return native_mod.classify_error(exc) == "transient"


class Watchdog:
    """Run a call with a wall-clock bound, on a disposable daemon thread.

    A hung ctypes call cannot be cancelled from Python; on timeout the
    worker thread is abandoned (daemon — it cannot block interpreter exit)
    and :class:`WatchdogTimeout` raises on the caller. ``timeout=None``
    disables the guard (zero threading overhead)."""

    def __init__(self, timeout: float | None):
        self.timeout = timeout

    def call(self, fn: Callable[[], Any], boundary: str):
        if not self.timeout:
            return fn()
        box: list = []
        done = threading.Event()

        def run():
            try:
                box.append(("ok", fn()))
            except BaseException as e:  # re-raised on the caller thread
                box.append(("err", e))
            finally:
                done.set()

        t = threading.Thread(
            target=run, daemon=True, name=f"gelly-watchdog-{boundary}"
        )
        t.start()
        if not done.wait(self.timeout):
            # Observable, not just raised: tests/bench read the fire
            # count off the bus; an installed tracer gets the instant.
            obs_bus.get_bus().emit(
                "resilience.watchdog_timeouts", boundary=boundary,
                timeout_s=self.timeout,
            )
            raise WatchdogTimeout(boundary, self.timeout)
        kind, payload = box[0]
        if kind == "err":
            raise payload
        return payload


class CheckpointManager:
    """Rotated ``ckpt-<position>.npz`` files with async writes.

    ``save`` snapshots device state to host *synchronously* (the state at
    that position, not whatever the device holds when the writer thread
    gets scheduled) and hands the file write to a single background worker
    with at-most-one write in flight — backpressure, not an unbounded
    queue. Write errors surface at the next ``save``/``flush`` and are
    retried inside the worker under ``retry``. ``load_latest`` walks the
    rotation newest-first, skipping torn/corrupt files.
    """

    def __init__(self, directory: str, keep: int = 3,
                 retry: RetryPolicy | None = None,
                 async_write: bool = True, seed: int = 0,
                 write_timeout: float | None = None,
                 prefix: str = "ckpt"):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        # ``prefix`` namespaces a rotation inside a SHARED directory —
        # the multi-tenant engine keeps one ``t<tenant>-<pos>.npz``
        # rotation per tenant in one dir instead of thousands of
        # directories. The trailing "-" separator keeps prefixes
        # prefix-free ("t7-*" never matches t77's files) — which only
        # holds if the prefix itself contains no "-": "t7-*" WOULD
        # match a prefix "t7-0"'s files, letting one rotation prune
        # and load another tenant's checkpoints. Callers with
        # arbitrary ids must escape them (engine/tenants.py does).
        if not prefix or "-" in prefix or any(
            sep and sep in prefix for sep in (os.sep, os.altsep)
        ):
            raise ValueError(
                f"prefix must be a non-empty file-name fragment "
                f"without '-' (the rotation separator), got {prefix!r}"
            )
        self.prefix = prefix
        self.directory = directory
        self.keep = keep
        self.retry = retry or RetryPolicy()
        # Watchdog for checkpoint I/O: a hung write surfaces as
        # WatchdogTimeout at the next flush instead of blocking the fold
        # loop forever. None = wait indefinitely.
        self.write_timeout = write_timeout
        self._rng = random.Random(seed)
        os.makedirs(directory, exist_ok=True)
        # A SIGKILL mid-write leaves save_checkpoint's atomic-rename temp
        # behind; it can never be the newest valid checkpoint (the rename
        # never happened), so reap it at takeover. Scoped to THIS
        # rotation's prefix: other rotations sharing the directory (the
        # multi-tenant engine keeps one per tenant, and admissions run
        # concurrently with checkpointing) may have writes in flight —
        # a directory-wide reap would delete their tmp mid-write.
        for stale in glob.glob(os.path.join(
            glob.escape(directory), glob.escape(self.prefix)
            + "-*.npz.tmp"
        )):
            try:
                os.unlink(stale)
            except OSError:
                pass
        self._async = async_write
        # Single-flight async write: (daemon thread, error box). A daemon
        # thread (not a ThreadPoolExecutor) so a write hung past the
        # timeout is abandoned cleanly and can never block interpreter
        # exit.
        self._pending: tuple | None = None
        # Consecutive failed/timed-out writes, reset by any write that
        # actually completes — the durability gauge the driver's
        # max_checkpoint_failures bound reads. (An abandoned writer that
        # eventually finishes resets it too: durability was achieved.)
        # Bumped from the async writer daemon AND from flush() on the
        # driver thread (a timed-out write counts as a miss before the
        # abandoned writer's own accounting runs): the read-modify-write
        # needs the lock or concurrent bumps lose updates (racecheck
        # RC002) and the abort bound under-counts misses.
        self.consecutive_failures = 0
        self._fail_lock = threading.Lock()

    def path_for(self, position: int) -> str:
        return os.path.join(
            self.directory, f"{self.prefix}-{position:012d}.npz"
        )

    def list(self) -> list[str]:
        """This rotation's checkpoint paths, oldest → newest
        (position-ordered; other prefixes sharing the directory are
        invisible to it)."""
        return sorted(glob.glob(os.path.join(
            glob.escape(self.directory), glob.escape(self.prefix)
            + "-*.npz"
        )))

    def save(self, state, position: int, meta: dict | None = None) -> None:
        host = jax.device_get(state)
        if not self._async:
            self._write(host, position, meta)
            return
        self.flush()
        box: list = []

        def writer():
            try:
                self._write(host, position, meta)
            except BaseException as e:  # surfaced at the next flush
                box.append(e)

        t = threading.Thread(target=writer, daemon=True, name="gelly-ckpt")
        t.start()
        self._pending = (t, box)

    def _write(self, host, position: int, meta: dict | None) -> None:
        try:
            self._write_inner(host, position, meta)
        except BaseException:
            with self._fail_lock:
                self.consecutive_failures += 1
            raise
        with self._fail_lock:
            self.consecutive_failures = 0

    def _write_inner(self, host, position: int, meta: dict | None) -> None:
        path = self.path_for(position)
        attempt = 0
        t0 = time.perf_counter()
        while True:
            try:
                faults_mod.inject("checkpoint_write", path=path)
                # Vetted exception to the daemon-durability rule: this
                # write is atomic (tmp + fsync + rename) and _rotate
                # validates the newest file before pruning its fallbacks,
                # so a daemon killed mid-write can only lose the newest
                # snapshot — never leave zero valid checkpoints.
                header = save_checkpoint(  # graphlint: disable=RC006
                    path, host, position=position, meta=meta
                )
                break
            except BaseException as e:
                attempt += 1
                if not default_retryable(e):
                    raise  # permanent (data) error: never a retry problem
                if attempt >= self.retry.max_attempts:
                    raise RetriesExhausted(
                        "checkpoint_write", attempt, e
                    ) from e
                time.sleep(self.retry.delay(attempt - 1, self._rng))
        # Durability currency on the bus: bytes written and write latency
        # are what the checkpoint cadence trades against fold throughput.
        obs_bus.publish_checkpoint(obs_bus.get_bus(), "resilience", path,
                                   t0=t0)
        # Torn-write simulation point: fires AFTER the file is durable so a
        # corrupt fault produces exactly the artifact load must survive.
        faults_mod.inject("checkpoint_corrupt", path=path)
        self._rotate(expected_crcs=header["crc32"])

    def _rotate(self, expected_crcs: list | None = None) -> None:
        files = self.list()
        if len(files) <= self.keep:
            return
        # Validate the just-written newest file BEFORE pruning its
        # fallbacks: a torn final write (the checkpoint_corrupt fault
        # models it) must never leave the rotation with ZERO valid
        # checkpoints. CRC detects the tear at load either way; the
        # point here is that the previous file is still there to fall
        # back to. The check is a HEADER-ONLY read (few KB — the zip
        # central directory lives at EOF, so any truncation fails it)
        # cross-checked against the CRCs computed during the write;
        # with no expected list (direct callers), fall back to the full
        # CRC read-back.
        try:
            if expected_crcs is not None:
                from .checkpoint import read_checkpoint_header

                header = read_checkpoint_header(files[-1])
                if header.get("crc32") != expected_crcs:
                    raise CheckpointCorruptError(
                        f"checkpoint {files[-1]}: on-disk header CRCs "
                        "differ from the just-written ones — torn or "
                        "clobbered write"
                    )
            else:
                load_checkpoint(files[-1])
        except (CheckpointCorruptError, OSError) as e:
            obs_bus.get_bus().emit(
                "resilience.rotation_skipped", path=files[-1],
                error=f"{type(e).__name__}: {e}"[:200],
            )
            logger.error(
                "newest checkpoint %s failed post-write validation (%s); "
                "keeping the previous rotation files as fallback",
                files[-1], e,
            )
            return
        for old in files[:-self.keep]:
            try:
                os.unlink(old)
            except OSError:
                pass

    def flush(self) -> None:
        """Wait for the in-flight write; re-raises its error, if any. A
        write still running after ``write_timeout`` raises
        :class:`WatchdogTimeout` — the daemon writer is abandoned (it can
        neither block the fold loop again nor interpreter exit)."""
        if self._pending is not None:
            (t, box), self._pending = self._pending, None
            t.join(self.write_timeout)
            if t.is_alive():
                # Neither completed nor failed yet — count the miss here
                # (_write's own accounting runs whenever it finishes).
                with self._fail_lock:
                    self.consecutive_failures += 1
                raise WatchdogTimeout("checkpoint_write", self.write_timeout)
            if box:
                raise box[0]

    def close(self) -> None:
        self.flush()

    def load_latest(self, like=None):
        """Newest valid checkpoint as ``(state, position, meta, path)``, or
        ``None`` when the rotation holds none. Corrupt/torn files are
        logged and skipped — the previous checkpoint in the rotation wins.
        """
        for path in reversed(self.list()):
            try:
                faults_mod.inject("checkpoint_read", path=path)
                state, position, meta = load_checkpoint(path, like=like)
                return state, position, meta, path
            except (CheckpointCorruptError, OSError,
                    faults_mod.FaultInjected) as e:
                # Unreadable, torn, or read-I/O-failed (the injected
                # checkpoint_read fault models the last): fall back.
                logger.warning(
                    "checkpoint %s unusable (%s); trying previous", path, e
                )
        return None


@dataclasses.dataclass(frozen=True)
class ResilienceConfig:
    """Knobs of :class:`ResilientRunner` (all have production defaults)."""

    checkpoint_every_chunks: int = 64
    checkpoint_every_seconds: float | None = None
    keep_checkpoints: int = 3
    retry: RetryPolicy = dataclasses.field(default_factory=RetryPolicy)
    # None disables the watchdog. Applied per guarded call (stage / step /
    # checkpoint), not to the whole run.
    watchdog_timeout: float | None = 60.0
    # Switch to fallback_step (and native.disable the stem, when known)
    # after this many CONSECUTIVE step failures classified as native.
    degrade_after: int = 2
    # Prefetch lookahead for the chunk source; 0 = synchronous pulls.
    prefetch_depth: int = 2
    # Source-iterator restarts allowed before the error is fatal.
    max_source_restarts: int = 3
    # Mid-stream checkpoint failures (hung write past the watchdog,
    # exhausted write retries) tolerated before the run aborts: the fold
    # keeps going with degraded durability, logged per miss. The forced
    # end-of-stream checkpoint is never tolerated — final state must be
    # durable.
    max_checkpoint_failures: int = 3
    seed: int = 0
    sleep: Callable[[float], None] = time.sleep
    clock: Callable[[], float] = time.monotonic


def _make_seekable(chunks) -> Callable[[int], Iterator]:
    """Normalize a chunk source to ``make_iter(position)``.

    Accepts an ``EdgeStream`` (``chunks_from``), a source with ``iter_from``
    (``core/io.EdgeChunkSource``), a callable ``position -> iterator``, or a
    plain re-iterable (islice skip — correct, just O(position) on restart).
    A single-shot iterator is accepted for one pass but any restart/re-open
    raises :class:`StreamFault` instead of silently re-reading an exhausted
    stream."""
    import itertools

    if callable(chunks) and not hasattr(chunks, "__iter__"):
        return chunks
    if hasattr(chunks, "chunks_from"):
        return chunks.chunks_from
    if hasattr(chunks, "iter_from"):
        return chunks.iter_from
    if iter(chunks) is chunks:
        # Single-shot iterator (generator): it can be opened exactly once —
        # a restart or a second resume attempt would silently re-read an
        # exhausted stream and "succeed" with missing data. Allow the one
        # open; fail LOUDLY on any re-open.
        opened = [False]

        def make_once(position: int) -> Iterator:
            if opened[0]:
                raise StreamFault(
                    "chunk source is a single-shot iterator and was already "
                    "consumed; source restart/resume needs a seekable or "
                    "re-iterable source (EdgeStream, EdgeChunkSource, a "
                    "callable position -> iterator, or a list)"
                )
            opened[0] = True
            return itertools.islice(chunks, position, None)

        return make_once

    def make_iter(position: int) -> Iterator:
        return itertools.islice(iter(chunks), position, None)

    return make_iter


class ResilientRunner:
    """Drive ``step(state, chunk) -> (state, emission)`` to completion,
    surviving transient failures and process death.

    ``chunks`` — an ``EdgeStream``, ``EdgeChunkSource``, callable
    ``position -> iterator``, or plain iterable. ``init_state`` — the
    initial state pytree or a zero-arg factory (also the resume template).
    ``stage(chunk) -> chunk`` — optional H2D/pre-processing hook, guarded
    as the ``"h2d"`` boundary. ``fallback_step`` — the numpy-path step the
    driver degrades to when native keeps failing.

    ``coordinator`` — an ``engine/coordination.Coordinator`` switches the
    driver to COORDINATED checkpoints on a multi-host mesh: at cadence
    the hosts run a checkpoint barrier (``agree_position`` — all agree
    on the max last-retired-chunk position), each folds its own
    partition up to the agreed position, and publishes its shard via
    the two-phase commit (prepared markers + leader-written manifest).
    Resume goes through ``Coordinator.recover``: manifest validation,
    CRC-checked own-shard load, and — with ``adopt_state`` (a
    ``combine(state, orphan_state) -> state``) — the degraded-capacity
    takeover of a permanently lost host's shards. Mutually exclusive
    with ``checkpoint_dir`` (the coordinator owns its store).
    Coordination failures (dead peer, commit timeout, barrier skew) are
    FATAL, never silently tolerated — a desynced mesh must surface; the
    per-host watchdog still bounds hung protocol calls.

    ``flatten_state`` — optional ``state -> state`` run at checkpoint
    cadence before each snapshot (coordinated or local): the periodic
    ``parent[parent]`` path flatten that keeps union-find transform
    chase depth bounded on long streams. The returned state REPLACES
    the live fold state (labels must be identical — e.g.
    ``ops/unionfind.pointer_jump`` on the parent leaf).

    ``run()`` returns the final state; ``emissions()`` yields
    ``(position, emission)`` for every non-None emission as it happens.
    """

    def __init__(
        self,
        step: Callable[[Any, Any], tuple[Any, Any]],
        chunks,
        init_state,
        *,
        checkpoint_dir: str | None = None,
        resume: bool = True,
        config: ResilienceConfig | None = None,
        stage: Callable[[Any], Any] | None = None,
        fallback_step: Callable[[Any, Any], tuple[Any, Any]] | None = None,
        meta: dict | None = None,
        coordinator=None,
        flatten_state: Callable[[Any], Any] | None = None,
        adopt_state: Callable[[Any, Any], Any] | None = None,
        reshard_source: Callable[[int, int], Any] | None = None,
    ):
        self._step = step
        self._make_iter = _make_seekable(chunks)
        self._init_state = init_state
        self._resume = resume
        self.config = config or ResilienceConfig()
        self._stage = stage
        self._fallback_step = fallback_step
        self._meta = dict(meta or {})
        self._rng = random.Random(self.config.seed)
        self._watchdog = Watchdog(self.config.watchdog_timeout)
        self._native_failures = 0
        self._degraded = False
        self._flatten = flatten_state
        self._adopt = adopt_state
        # Ingest-side re-shard hook for the coordinated degraded re-join
        # (``gelly_tpu.ingest.ShardRoutingTable.reroute`` fits it): when
        # recover() adopts a lost host's state shards, this reroutes the
        # lost host's READER shards to the same survivors.
        self._reshard_source = reshard_source
        self.coordinator = coordinator
        if coordinator is not None and checkpoint_dir is not None:
            raise ValueError(
                "pass checkpoint_dir OR coordinator, not both: the "
                "coordinator owns its shared store (path-per-host epoch "
                "layout), a local rotation dir would shadow it"
            )
        # Coordination calls get a LARGER watchdog budget than plain
        # boundaries: a barrier legitimately waits up to the protocol's
        # own barrier_timeout, whose error names the missing/dead hosts
        # — the generic WatchdogTimeout must only fire for a genuine
        # hang (e.g. an injected hang fault, a wedged fsync), never
        # first, or it masks the actionable diagnosis.
        self._barrier_watchdog = Watchdog(None)
        if coordinator is not None:
            wt = self.config.watchdog_timeout
            self._barrier_watchdog = Watchdog(
                None if wt is None
                else wt + 2 * coordinator.config.barrier_timeout
            )
        self.manager = None
        if checkpoint_dir is not None:
            self.manager = CheckpointManager(
                checkpoint_dir,
                keep=self.config.keep_checkpoints,
                retry=self.config.retry,
                seed=self.config.seed,
                write_timeout=self.config.watchdog_timeout,
            )
        self.position = 0  # chunks folded into the current state
        self.stats = {
            "chunks": 0, "retries": 0, "checkpoints": 0,
            "checkpoint_failures": 0, "restarts": 0,
            "resumed_from": None, "degraded": False,
        }

    # ------------------------------------------------------------------ #
    # guarded calls

    def _guard(self, boundary: str, fn: Callable[[], Any]):
        """Retry ``fn`` under the watchdog with exponential backoff."""
        policy = self.config.retry
        attempt = 0

        def guarded():
            # Injection runs INSIDE the watchdog guard: a kind="hang" fault
            # must be caught by the timeout exactly like a real hung call.
            faults_mod.inject(boundary)
            return fn()

        while True:
            try:
                return self._watchdog.call(guarded, boundary)
            except BaseException as e:
                attempt += 1
                if boundary == "step" and self._maybe_degrade(e):
                    # Same chunk re-attempted on the fallback path; the
                    # failed attempt left no state behind (step is pure).
                    continue
                if not default_retryable(e):
                    raise
                if attempt >= policy.max_attempts:
                    raise RetriesExhausted(boundary, attempt, e) from e
                self.stats["retries"] += 1
                obs_bus.get_bus().emit(
                    "resilience.retries", boundary=boundary,
                    attempt=attempt,
                    error=f"{type(e).__name__}: {e}"[:200],
                )
                delay = policy.delay(attempt - 1, self._rng)
                logger.warning(
                    "boundary '%s' attempt %d/%d failed (%s: %s); "
                    "retrying in %.3fs", boundary, attempt,
                    policy.max_attempts, type(e).__name__, e, delay,
                )
                self.config.sleep(delay)

    def _maybe_degrade(self, exc: BaseException) -> bool:
        """Degradation ladder: repeated native step errors switch the fold
        to the numpy fallback (and disable the native stem process-wide so
        codec probes stop choosing it). Returns True when the step was
        swapped and the chunk should be re-attempted immediately."""
        if self._degraded or self._fallback_step is None:
            return False
        if native_mod.classify_native(exc) is None:
            return False
        self._native_failures += 1
        if self._native_failures < self.config.degrade_after:
            return False
        stem = getattr(exc, "stem", None)
        if stem:
            native_mod.disable(stem, reason=f"degraded mid-stream: {exc}")
        logger.warning(
            "native step failed %d consecutive times (%s: %s); degrading "
            "to the numpy fallback fold", self._native_failures,
            type(exc).__name__, exc,
        )
        self._step = self._fallback_step
        self._degraded = True
        self.stats["degraded"] = True
        obs_bus.get_bus().emit(
            "resilience.degradations", stem=stem or "",
            failures=self._native_failures,
            error=f"{type(exc).__name__}: {exc}"[:200],
        )
        return True

    # ------------------------------------------------------------------ #
    # the fold loop

    def _initial_state(self):
        state = (self._init_state()
                 if callable(self._init_state) else self._init_state)
        if self.coordinator is not None and self._resume:
            found = self._barrier_watchdog.call(
                lambda: self.coordinator.recover(
                    like=state, adopt=self._adopt,
                    reshard=self._reshard_source,
                ),
                "barrier",
            )
            if found is not None:
                rec_state, self.position, meta = found
                if rec_state is not None:
                    # None = a NEW host joining a smaller committed
                    # group: fresh state, barrier-agreed position.
                    state = jax.tree.map(np.asarray, rec_state)
                self._meta.update(
                    {k: v for k, v in meta.items() if k not in self._meta}
                )
                # The manifest IS the coordinated resume record (the
                # shard path varies per host and may be an adopted set).
                self.stats["resumed_from"] = (
                    self.coordinator.store.manifest_path
                )
                logger.info(
                    "coordinated resume at chunk %d (epoch %s)",
                    self.position, self.coordinator.committed_epoch,
                )
        elif self.manager is not None and self._resume:
            found = self.manager.load_latest(like=state)
            if found is not None:
                state, self.position, meta, path = found
                state = jax.tree.map(np.asarray, state)
                self._meta.update(
                    {k: v for k, v in meta.items() if k not in self._meta}
                )
                self.stats["resumed_from"] = path
                logger.info(
                    "resuming from %s at chunk %d", path, self.position
                )
        return state

    def emissions(self) -> Iterator[tuple[int, Any]]:
        """Run the fold; yield ``(position, emission)`` for each non-None
        emission. The final state is left in ``self.state``."""
        cfg = self.config
        state = self._initial_state()
        self.state = state
        start = self.position
        last_ckpt_pos = start
        last_ckpt_time = cfg.clock()
        # Serving-plane telemetry (same zero-cost-when-disabled guard as
        # the engine executor): ingress stamps ride the runner's
        # exactly-once positions, so the resilient driver reports the
        # same e2e watermarks/histograms as the pipelined path.
        wm_bus = obs_bus.get_bus()
        wm = wm_bus.watermarks if obs_bus.telemetry_on() else None
        if wm is not None:
            wm.seed("stream", start)

        def should_restart(exc: BaseException) -> bool:
            ok = default_retryable(exc)
            if ok:
                self.stats["restarts"] += 1
                obs_bus.get_bus().emit(
                    "resilience.source_restarts", position=self.position,
                    error=f"{type(exc).__name__}: {exc}"[:200],
                )
                logger.warning(
                    "chunk source failed (%s: %s); restarting at chunk %d",
                    type(exc).__name__, exc, self.position,
                )
            return ok

        def source_iter(pos: int) -> Iterator:
            faults_mod.inject("source")
            return self._make_iter(pos)

        chunk_iter = restartable_prefetch(
            source_iter,
            depth=cfg.prefetch_depth,
            start=start,
            max_restarts=cfg.max_source_restarts,
            should_restart=should_restart,
            position=lambda: self.position,
        )
        barrier: tuple[int, int] | None = None  # (epoch, agreed position)
        try:
            for chunk in chunk_iter:
                if wm is not None:
                    wm.stamp("stream", self.position)
                if self._stage is not None:
                    chunk = self._guard(
                        "h2d", lambda c=chunk: self._stage(c)
                    )
                state, emission = self._guard(
                    "step", lambda s=state, c=chunk: self._step(s, c)
                )
                # The degrade ladder counts CONSECUTIVE native failures; a
                # chunk that eventually folded clean resets it.
                self._native_failures = 0
                self.state = state
                self.position += 1
                if wm is not None:
                    wm.retire_fold("stream", self.position, bus=wm_bus,
                                   prefix="resilience")
                self.stats["chunks"] = self.position - start
                if emission is not None:
                    yield self.position, emission
                due = (
                    self.position - last_ckpt_pos
                    >= cfg.checkpoint_every_chunks
                )
                if not due and cfg.checkpoint_every_seconds is not None:
                    due = (cfg.clock() - last_ckpt_time
                           >= cfg.checkpoint_every_seconds)
                if self.coordinator is not None:
                    self.coordinator.maybe_beat()
                    if barrier is None and due:
                        # Checkpoint barrier: agree on max(last-retired)
                        # across hosts; this host may still be behind the
                        # agreed position — keep folding until it retires
                        # it, THEN publish. Every host snapshots the same
                        # position.
                        barrier = self._barrier_watchdog.call(
                            lambda p=self.position:
                                self.coordinator.agree_position(p),
                            "barrier",
                        )
                    if barrier is not None and self.position >= barrier[1]:
                        state = self._checkpoint_coordinated(
                            state, *barrier
                        )
                        self.state = state
                        barrier = None
                        last_ckpt_pos = self.position
                        last_ckpt_time = cfg.clock()
                elif self.manager is not None and due:
                    state = self._checkpoint(state)
                    self.state = state
                    last_ckpt_pos = self.position
                    last_ckpt_time = cfg.clock()
            if self.coordinator is not None:
                if barrier is not None:
                    # The stream ended BELOW a pending barrier position:
                    # another host proposed more chunks than this
                    # partition holds. Peers are waiting for this host's
                    # shard at a position it can never reach — surface
                    # the skew instead of deadlocking them.
                    raise CoordinationError(
                        f"stream exhausted at chunk {self.position} but "
                        f"the checkpoint barrier agreed on {barrier[1]} "
                        "— coordinated partitions must have equal chunk "
                        "counts"
                    )
                if self.position > last_ckpt_pos:
                    epoch, agreed = self._barrier_watchdog.call(
                        lambda p=self.position:
                            self.coordinator.agree_position(p),
                        "barrier",
                    )
                    if agreed != self.position:
                        raise CoordinationError(
                            f"hosts disagree on the final position "
                            f"({agreed} vs {self.position}) — coordinated "
                            "partitions must have equal chunk counts"
                        )
                    state = self._checkpoint_coordinated(
                        state, epoch, agreed, final=True
                    )
                    self.state = state
            elif self.manager is not None:
                if self.position > last_ckpt_pos:
                    state = self._checkpoint(state, final=True)
                    self.state = state
                self.manager.close()
            elif wm is not None:
                # No durability point configured: end-of-stream is the
                # retirement point — drain the ledger so the watermark
                # never reads a completed run as backlog.
                wm.retire_durable("stream", self.position, bus=wm_bus,
                                  prefix="resilience")
        except BaseException:
            # Leave the newest durable checkpoint in place for the next
            # incarnation; just stop the writer cleanly.
            if self.manager is not None:
                try:
                    self.manager.close()
                except BaseException:
                    logger.exception("checkpoint writer shutdown failed")
            raise
        finally:
            # The runner owns the coordinator's lifecycle for the run:
            # closing stops the lease beat thread (peers see this host
            # depart within lease_ttl) and drops the observability
            # registration — one Coordinator per incarnation.
            if self.coordinator is not None:
                try:
                    self.coordinator.close()
                except BaseException:
                    logger.exception("coordinator shutdown failed")

    def _flattened(self, state):
        """Apply the cadenced path flatten (when configured) — the
        returned state replaces the live fold state, so chase depth
        stays bounded across the whole stream, not just in snapshots."""
        if self._flatten is None:
            return state
        return self._flatten(state)

    def _checkpoint(self, state, final: bool = False):
        """Cadenced snapshot. A failed MID-STREAM checkpoint (hung write,
        exhausted write retries) degrades durability but must not kill an
        otherwise healthy fold — tolerated up to ``max_checkpoint_failures``
        consecutive misses; the end-of-stream checkpoint always raises.
        Returns the (possibly flattened) state the fold continues with."""
        state = self._flattened(state)
        try:
            self.manager.save(
                state, self.position,
                meta={**self._meta, "wall_time": time.time()},
            )
        except (WatchdogTimeout, RetriesExhausted):
            self.stats["checkpoint_failures"] += 1
            consecutive = self.manager.consecutive_failures
            obs_bus.get_bus().emit(
                "resilience.checkpoint_misses", position=self.position,
                consecutive=consecutive, final=final,
            )
            if final or consecutive >= self.config.max_checkpoint_failures:
                raise
            logger.error(
                "checkpoint at position %d failed (%d consecutive miss(es),"
                " tolerating up to %d); durability degraded, fold continues",
                self.position, consecutive,
                self.config.max_checkpoint_failures,
            )
            return state
        self.stats["checkpoints"] += 1
        self._retire_durable()
        return state

    def _retire_durable(self) -> None:
        """Durability point: the e2e ledger retires every position the
        just-published snapshot covers and the low watermark advances.
        (Async writers retire at save() return — the write is in
        flight; the bus's completed-write counters stay the durability
        authority.)"""
        if not obs_bus.telemetry_on():
            return
        b = obs_bus.get_bus()
        b.watermarks.retire_durable("stream", self.position, bus=b,
                                    prefix="resilience")
        b.gauge("engine.backlog_age_s",
                round(b.watermarks.backlog_age("stream"), 6))

    def _checkpoint_coordinated(self, state, epoch: int, agreed: int,
                                final: bool = False):
        """Publish this host's shard at the barrier-agreed position via
        the coordinator's two-phase commit. Unlike the local path,
        failures here are FATAL: a host that silently skips a
        coordinated epoch desyncs the whole group (peers block on its
        prepared marker), so the error must surface and take the
        incarnation down — recovery restarts from the previous
        committed epoch."""
        if self.position != agreed:
            raise CoordinationError(
                f"coordinated checkpoint at position {self.position} but "
                f"the barrier agreed on {agreed} — driver bug"
            )
        state = self._flattened(state)
        host = jax.device_get(state)
        self._barrier_watchdog.call(
            lambda: self.coordinator.publish(
                epoch, host, self.position,
                meta={**self._meta, "wall_time": time.time()},
            ),
            "barrier",
        )
        self.stats["checkpoints"] += 1
        # Same durability point as the local path: a committed barrier
        # epoch retires this host's ledger up to the agreed position —
        # without it a coordinated run's stamps accumulate forever and
        # backlog_age reads a healthy multi-host stream as unbounded
        # backlog.
        self._retire_durable()
        return state

    def run(self):
        """Drain the stream; return the final state pytree."""
        for _ in self.emissions():
            pass
        return self.state


def resilient_fold(step, chunks, init_state, **kw):
    """Functional shorthand: run :class:`ResilientRunner` to completion and
    return the final state."""
    return ResilientRunner(step, chunks, init_state, **kw).run()
