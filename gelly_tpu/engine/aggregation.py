"""The summary-aggregation engine — the heart of the framework.

Re-owns the reference's ``SummaryAggregation`` plugin contract
(``M/SummaryAggregation.java:22-59``): an algorithm supplies only

  {fold, combine, transform, init, transient}

and the engine decides the physical plan. The reference's ``run()`` builds a
Flink dataflow (``SummaryBulkAggregation.run``, ``:68-90``):

  map(PartitionMapper) → keyBy(partition) → timeWindow → fold(initial, partial)
  → timeWindowAll → reduce(combine) → Merger(parallelism=1) → map(transform)

Here the same plan becomes a TPU execution schedule:

  split chunk across shards (→ PartitionMapper) →
  per-device jitted chunk fold into local summary (→ window fold) →
  at each window boundary, an ICI collective merge — butterfly merge-tree
  (→ SummaryTreeReduce) or all_gather+stacked merge (→ timeWindowAll.reduce) →
  Merger semantics on the replicated global summary →
  transform → chunk-grained emission.

Fold functions are **chunk-vectorized** (``fold(summary, EdgeChunk) -> summary``)
rather than per-edge; :func:`edges_fold_adapter` wraps a per-edge
``foldEdges(acc, src, dst, val)`` UDF (the reference's ``EdgesFold``,
``M/EdgesFold.java:33-48``) into a ``lax.scan`` chunk fold for API parity.

Windows: ``merge_every`` chunks (count-based cadence, the throughput path) or
``window_ms`` over the stream's timestamps (tumbling event/ingestion-time
windows matching ``timeWindow(timeMillis)``). Both trigger the same
merge+Merger+emit sequence.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from functools import partial
from typing import Any, Callable, Iterator, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.chunk import EdgeChunk, split_chunk_host
from ..obs import bus as obs_bus
from ..obs import tracing as obs_tracing
from ..parallel import collectives, mesh as mesh_lib, partition
from ..parallel.mesh import SHARD_AXIS
from . import faults as faults_mod

Summary = Any


@dataclasses.dataclass(eq=False)
class SummaryAggregation:
    """The four-knob plugin contract (M/SummaryAggregation.java:31-55).

    - ``init()`` → fresh summary pytree (fixed shapes).
    - ``fold(summary, chunk)`` → summary: vectorized per-shard edge fold
      (the EdgesFold updateFun, chunk-at-a-time).
    - ``combine(a, b)`` → summary: associative+commutative cross-partition
      merge (the ReduceFunction combineFun).
    - ``transform(summary)`` → emission (optional MapFunction).
    - ``transient`` — when True the global summary resets every window
      (M/SummaryAggregation.java:113-115); otherwise it accumulates.
    - ``merge_stacked`` — optional ``stacked -> summary`` merging all K
      shard summaries at once (leading axis K); when present the engine
      uses all_gather + merge_stacked instead of the butterfly combine.
    """

    init: Callable[[], Summary]
    fold: Callable[[Summary, EdgeChunk], Summary]
    combine: Callable[[Summary, Summary], Summary]
    transform: Callable[[Summary], Any] | None = None
    transient: bool = False
    # transform is jitted per plan (device transforms, the default); set
    # False for transforms doing host-side / non-traceable work.
    jit_transform: bool = True
    # True when transform's output may PASS THROUGH leaves of the live
    # summary unchanged (e.g. a fused multi-query plan whose
    # transform-less sub-query emits its running state): the accumulate
    # plan then keeps fold donation OFF, exactly like the transform-less
    # accumulate plan — a donated next fold would delete the consumer's
    # held emission out from under it (see the donation contract below).
    transform_may_alias: bool = False
    merge_stacked: Callable[[Summary], Summary] | None = None
    # Optional ingest codec: ``host_compress(chunk) -> payload`` runs on the
    # prefetch thread and pre-aggregates a chunk into a compact numpy pytree
    # (the reference's per-partition partial fold relocated to the ingest
    # side, M/SummaryBulkAggregation.java:76-80); ``fold_compressed(summary,
    # stacked_payload)`` folds a [K]-stacked batch of payloads on device.
    # Both must be set for the codec path to engage; it cuts H2D bytes by
    # 1-2 orders of magnitude, which is the scarce resource on the
    # host->device link. Ignored in window mode (payloads carry no
    # per-edge timestamps).
    host_compress: Callable[[EdgeChunk], Any] | None = None
    fold_compressed: Callable[[Summary, Any], Summary] | None = None
    # Optional payload stacker for variable-length codec payloads:
    # ``stack_payloads(list_of_payloads, groups) -> stacked pytree``
    # (leading axis >= groups, a multiple of it). Sparse touched-slot
    # codecs use it to pad each batch to a power-of-two bucket capacity
    # (wire bytes track the actual touched count; the handful of bucket
    # shapes keep jit retraces bounded), and MAY pre-combine the batch
    # down to ``groups`` payloads on the host (a SummaryTreeReduce
    # partial-merge level on the ingest side). ``groups`` is the mesh
    # shard count (the batch axis splits across devices); 1 on a single
    # shard. None = leaves are equal-shape and np.stack-ed generically.
    stack_payloads: Callable[..., Any] | None = None
    # Optional host-side validator for PRODUCER-COMPRESSED payloads
    # (wire DATA_COMPRESSED frames, tenant submit_payload, the engine's
    # precompressed=True staging): ``codec_payload_check(payload)``
    # raises ValueError on a payload the device fold could only
    # mis-index SILENTLY — out-of-range ids scatter-drop/clamp on
    # device, the exact corruption mode payload_to_chunk's
    # vertex_capacity guard exists to prevent on the raw wire. Checked
    # at the staging/enqueue boundary so the error lands on the
    # producer side, never the scheduler/fold thread.
    codec_payload_check: Callable[[Any], None] | None = None
    # Wire/stacking pad values for the codec payload's VARIABLE-LENGTH
    # dict keys (e.g. the sparse CC pairs' {"v": -1, "r": 0}): consumers
    # that stack per-chunk payloads themselves — the tenant engine's
    # compressed tiers, which stack one payload per LANE instead of K
    # per unit — pad each key to a shared bucket with these values so
    # the padded lanes fold as no-ops exactly like the plan's own
    # stack_payloads padding. None with a dict payload means every key
    # is fixed-shape (stacked as-is); ndarray payloads never need it.
    codec_pad_values: dict | None = None
    # True when stack_payloads mutates per-run state in STREAM order (the
    # compact plans' persistent id assignment): the engine then numbers
    # codec units from 0 per run and passes ``seq=`` to stack_payloads so
    # concurrent ingest workers can take the stateful step in order
    # (everything stateless in the stacker stays parallel).
    stack_ordered: bool = False
    # With stack_ordered, a unit that fails BEFORE taking its assignment
    # turn would park every later unit's worker in await_turn forever; the
    # engine calls this hook (with the failed unit's seq) from the staging
    # error path so the codec can release the turn (idempotent if the
    # unit already completed it).
    on_stage_error: Callable[[int], None] | None = None
    # With stack_ordered, cumulative seconds stagers have spent blocked in
    # the codec's ordered-turn gate (CompactIdSession.await_turn). The
    # engine samples it at run start and teardown and reattributes the
    # delta from ``ingest_compress`` to a ``codec_wait`` timer stage:
    # turn-wait is pipeline serialization, not compress work, and booking
    # it as busy would overstate the serial-cost side of the overlap
    # accounting (a serial run never waits here).
    ordered_wait_s: Callable[[], float] | None = None
    # SummaryTreeReduce's degree knob (M/SummaryTreeReduce.java:75): when
    # set, the cross-shard combine runs as a two-phase hierarchical tree —
    # groups of S/degree shards merge first (ICI-local), then across groups
    # (DCN on multi-host meshes). None = flat butterfly / gather merge.
    merge_degree: int | None = None
    # Stateful-codec lifecycle hooks (e.g. the compact-space CC plan's
    # host id session): ``on_run_start()`` fires at the start of every
    # run_aggregation generator (fresh run = fresh codec state — one live
    # run per aggregation instance at a time); ``on_resume(summary)`` fires
    # after a checkpoint load so host codec state can be rebuilt from the
    # restored device summary.
    on_run_start: Callable[[], None] | None = None
    on_resume: Callable[[Summary], None] | None = None
    # Device-fold kernel backend the plan's fold closures were built for
    # ("xla" | "pallas"): set by the library plan builders (e.g.
    # connected_components(fold_backend=...)), recorded here so the
    # engine's compiled-plan cache keys on it — the same aggregation
    # instance re-jits (rather than silently reusing stale executables)
    # if a caller rebuilds its folds for a different backend.
    fold_backend: str = "xla"
    # Cross-shard window-merge strategy ("replicated" | "delta" | "auto").
    # The replicated merges (butterfly / hierarchical tree / gather) move
    # FULL per-shard summaries — cost ∝ capacity per window regardless of
    # how little the window touched. A plan that supplies ``merge_delta``
    # can instead exchange only the dirty entries its folds marked:
    #
    # - ``merge_dirty_count(local_summary) -> i32`` — per-shard count of
    #   dirty entries (pure jnp; the engine wraps it in shard_map and
    #   reads the max once per window close to size the gather bucket);
    # - ``merge_delta(base, local_summary, bucket) -> summary`` — runs
    #   per-shard INSIDE shard_map: compact this shard's dirty rows to
    #   ``bucket`` lanes (collectives.compact_delta), all_gather every
    #   shard's rows (collectives.gather_delta), and apply them to the
    #   replicated ``base`` (the carried global summary). Replaces BOTH
    #   the cross-shard merge and the Merger combine in one program, so
    #   window-merge cost is ∝ hooks-since-last-merge, not capacity.
    #
    # "auto" decides per window from the measured count: delta while the
    # gathered rows (S * bucket) stay under ``merge_delta_auto_rows``,
    # else the plan's replicated merge. Deltas are measured against a
    # window-fresh locals (init()), which the engine guarantees by
    # rebuilding locals at every window close. Like fold_backend, the
    # compiled-plan cache keys on merge_mode.
    merge_mode: str = "replicated"
    merge_delta: Callable[..., Summary] | None = None
    merge_dirty_count: Callable[[Summary], Any] | None = None
    merge_delta_auto_rows: int | None = None
    # True for plans whose fold exists ONLY through the ingest codec (the
    # compact-space plans: raw chunks carry ids the summary's compact space
    # has no mapping for). The engine then refuses — loudly, at plan time —
    # any configuration where the codec cannot engage (window_ms mode, or a
    # batch that cannot align with the shard count) instead of silently
    # falling back to the raw fold.
    requires_codec: bool = False
    # Optional cadenced path flatten: ``flatten(summary) -> summary``
    # with IDENTICAL labels (e.g. unionfind.pointer_jump on the parent
    # leaf). The pair-sized folds (union_pairs_rooted/star) and the
    # dirty-delta merge deliberately skip the O(capacity) global flatten
    # per dispatch, so transform chase depth grows O(1) per window on
    # long streams; the engine runs this (jitted) once per CHECKPOINT
    # cadence — full-capacity work amortized over the checkpoint
    # interval, keeping chase depth bounded for the whole stream. The
    # flattened summary REPLACES the live state (and is what the
    # checkpoint snapshots).
    flatten: Callable[[Summary], Summary] | None = None
    # Declares fold(combine(a, b), c) == combine(a, fold(b, c)) — folding
    # into an already-combined summary equals combining afterwards (true
    # for pure edge-set summaries: CC forests, parity forests, degree
    # vectors). With it, the single-shard non-transient plan carries ONE
    # running summary across windows and emits transform(local) directly,
    # skipping the per-window Merger combine — which for forest summaries
    # is a full-capacity union fixpoint per window close. Emissions are
    # identical; only the physical plan changes.
    fold_accumulates: bool = False
    name: str = "aggregation"


# Auto-codec threshold: below this slot-space size a dense per-chunk
# payload (n_v * ~4 bytes) is smaller/cheaper than touched-slot pairs;
# above it the dense payload inverts the codec's wire compression.
SPARSE_CODEC_MIN_CAPACITY = 1 << 20

# Smallest dirty-delta gather bucket (pow-2 ladder floor): keeps the
# per-window program count bounded and lets merge_mode="auto" prove at
# PLAN time that delta can never win on tiny capacities (S * floor already
# above the plan's auto-rows bound) — those plans skip the count program
# entirely instead of paying a per-window D2H for a foregone decision.
DELTA_MERGE_MIN_BUCKET = 256


def available_cores() -> int:
    """Cores this process may actually run on (affinity/cgroup-aware)."""
    import os

    try:
        return len(os.sched_getaffinity(0)) or 1
    except AttributeError:
        return os.cpu_count() or 1


def resolve_sparse_codec(codec: str, vertex_capacity: int) -> bool:
    """Shared ``codec=`` knob semantics for the ingest codecs: validate
    and resolve ``"auto"``/``"dense"``/``"sparse"`` to a bool (sparse?).
    """
    if codec not in ("auto", "dense", "sparse"):
        raise ValueError(f"codec must be auto/dense/sparse, got {codec}")
    return codec == "sparse" or (
        codec == "auto" and vertex_capacity >= SPARSE_CODEC_MIN_CAPACITY
    )


def group_combine_payloads(payloads: list, groups: int,
                           combine_fn: Callable[[list], dict],
                           empty_payload: dict) -> list:
    """Host pre-combine for a combining ``stack_payloads``: when the
    batch is larger than ``groups``, merge it down to exactly ``groups``
    payloads (ceil-sized contiguous groups, padded with ``empty_payload``
    rows so the mesh split sees ``groups`` rows).
    ``combine_fn(group_payloads) -> payload``.

    ``len(payloads) <= groups`` returns the list UNCHANGED (no padding):
    the engine's stage path always pre-pads batches to a multiple of the
    shard count, which is what the downstream mesh reshape needs — a
    caller with a short, non-multiple list must pad before the split.
    """
    if len(payloads) <= groups:
        return payloads
    size = -(-len(payloads) // groups)
    combined = [
        combine_fn(payloads[i:i + size])
        for i in range(0, len(payloads), size)
    ]
    while len(combined) < groups:
        combined.append(empty_payload)
    return combined


def bucket_stack_payloads(payloads: list, pad_values: dict,
                          min_bucket: int = 1024,
                          quantum: int | None = None,
                          per_key: dict | None = None) -> dict:
    """Stack variable-length dict payloads to a shared power-of-two bucket.

    ``pad_values`` maps the variable-length array keys to their padding
    value; those leaves are padded to ``max(min_bucket,
    next_pow2(longest))`` before stacking, so the stacked shape (and hence
    the jitted fold program) takes only O(log) distinct values across a
    stream. Keys not in ``pad_values`` (per-payload scalars/fixed shapes)
    are stacked as-is. This is the wire format of the sparse touched-slot
    codecs: payload bytes ∝ the chunk's actual touched count, never the
    vertex capacity.

    ``quantum`` switches the bucket ladder from powers of two to multiples
    of ``quantum``: distinct shapes stay bounded (≤ longest/quantum per
    stream) while padding waste drops from up-to-2x to ≤ quantum lanes —
    the fold kernels' gather cost scales with PADDED lanes, so at
    multi-M pair counts the pow-of-two ladder would buy compile-cache
    stability with up to 2x device work.

    ``per_key`` maps a padded key to its own ``(min_bucket, quantum)``:
    keys whose natural length is far below the others' (e.g. per-segment
    lengths vs per-pair members) then get their own bucket ladder instead
    of inheriting the largest key's capacity — padding a short leaf to
    the long leaves' bucket was measured as ~1/3 of the compact codec's
    wire bytes. Keys not listed share the default ladder as before.
    """
    def _cap(longest, mb, q):
        if q:
            return max(mb, -(-longest // q) * q)
        return max(mb, 1 << max(0, longest - 1).bit_length())

    per_key = per_key or {}
    shared = [k for k in pad_values if k not in per_key]
    longest = max(
        (p[k].shape[0] for p in payloads for k in shared), default=0
    )
    caps = {k: _cap(longest, min_bucket, quantum) for k in shared}
    for k, (mb, q) in per_key.items():
        lk = max((p[k].shape[0] for p in payloads), default=0)
        caps[k] = _cap(lk, mb, q)
    out = {}
    for key in payloads[0]:
        if key in pad_values:
            stacked = np.full(
                (len(payloads), caps[key]), pad_values[key],
                dtype=payloads[0][key].dtype,
            )
            for i, p in enumerate(payloads):
                stacked[i, : p[key].shape[0]] = p[key]
            out[key] = stacked
        else:
            out[key] = np.stack([p[key] for p in payloads])
    return out


def sparse_payload_id_check(vertex_capacity: int, *keys: str):
    """Build a ``codec_payload_check`` (see the SummaryAggregation
    field) validating that every listed key of a sparse codec payload
    carries vertex ids in ``[0, vertex_capacity)`` — the
    ``payload_to_chunk`` range guard's twin for pre-compressed ingest,
    where the payload never passes through a chunk. O(k) numpy min/max
    per key, run on the producer/staging side."""
    def check(payload) -> None:
        if not isinstance(payload, dict):
            raise ValueError(
                f"compressed payload must be a dict of arrays, got "
                f"{type(payload).__name__} — was it compressed by a "
                "different plan/codec?"
            )
        for key in keys:
            if key not in payload:
                raise ValueError(
                    f"compressed payload is missing key {key!r} — was "
                    "it compressed by a different plan/codec?"
                )
            a = np.asarray(payload[key])
            if a.size == 0:
                continue
            lo, hi = int(a.min()), int(a.max())
            if lo < 0 or hi >= vertex_capacity:
                bad = lo if lo < 0 else hi
                raise ValueError(
                    f"compressed payload key {key!r} carries vertex id "
                    f"{bad} out of range for vertex_capacity "
                    f"{vertex_capacity} — compressed by a plan with a "
                    "different capacity? (an out-of-range id would "
                    "silently drop/clamp in the device scatter)"
                )

    return check


def _payload_nbytes(payload) -> int:
    """Host bytes of a staged unit's pytree — span attribution only
    (called on the tracer-enabled path, never the bare unit path)."""
    return int(sum(getattr(l, "nbytes", 0)
                   for l in jax.tree.leaves(payload)))


def _group_edges(group) -> int:
    """Valid-edge count of a unit's chunk group — span/heartbeat
    attribution only (one O(chunk) bool sum per chunk, tracer-enabled
    path only)."""
    return int(sum(int(np.asarray(c.valid).sum()) for c in group))


def edges_fold_adapter(fold_edges: Callable, *, with_value: bool = True):
    """Wrap a per-edge UDF ``foldEdges(acc, src, dst[, val])`` into a chunk fold.

    Parity adapter for the reference's EdgesFold contract
    (M/EdgesFold.java:33-48): runs a sequential ``lax.scan`` over the chunk in
    stream order. Library algorithms should prefer native vectorized folds;
    this exists so arbitrary user folds still run on device.
    """

    def fold(summary, chunk: EdgeChunk):
        def step(acc, inp):
            src, dst, val, ok = inp
            out = (
                fold_edges(acc, src, dst, val)
                if with_value
                else fold_edges(acc, src, dst)
            )
            acc2 = jax.tree.map(
                lambda new, old: jnp.where(ok, new, old), out, acc
            )
            return acc2, None

        acc, _ = jax.lax.scan(
            step, summary, (chunk.src, chunk.dst, chunk.val, chunk.valid)
        )
        return acc

    return fold


class SummaryStream:
    """Lazy stream of per-window emissions from a running aggregation.

    Iterating yields ``transform(global_summary)`` once per closed window
    (plus once at end-of-stream for the final partial window). ``result()``
    drains the stream and returns the last emission — the reference tests'
    "take the final summary" oracle
    (T/example/test/ConnectedComponentsTest.java:65-81).
    """

    def __init__(self, gen_fn: Callable[[], Iterator]):
        self._gen_fn = gen_fn

    def __iter__(self):
        return self._gen_fn()

    def result(self):
        last = None
        for last in self:
            pass
        return last


class WindowedStream(SummaryStream):
    """A :class:`SummaryStream` over a pane ring (``windowed=W``), plus
    the queryable epoch handle: ``snapshot()`` returns the latest
    ``{"window", "labels"}`` emission under a lock, readable from any
    thread while the stream advances. Staleness is bounded by ONE pane
    (the value published at the most recent pane close) — the same
    contract as tenant/multiquery snapshots. Returns ``None`` before
    the first pane closes.
    """

    def __init__(self, gen_fn: Callable[[], Iterator], holder: dict):
        super().__init__(gen_fn)
        self._holder = holder

    def snapshot(self):
        with self._holder["lock"]:
            val = self._holder["val"]
        obs_bus.get_bus().inc("windows.snapshot_reads")
        return val


def _compiled_plan(agg: SummaryAggregation, m):
    # Jitted physical plans are memoized on the aggregation instance itself:
    # jax.jit caches executables by function identity, so rebuilding the
    # closures on every run_aggregation call would recompile the whole plan
    # each time (~10s/program over the TPU tunnel). Storing on the instance
    # ties the cache (and its compiled executables) to the agg's lifetime.
    # EVERY scalar knob this builder reads must appear in the key (the
    # plancheck PC101 contract): a knob read but not keyed means mutating
    # it on a live instance silently returns the stale compiled plan.
    key = (tuple(d.id for d in m.devices.flat), m.axis_names,
           agg.fold_backend, agg.merge_mode, agg.merge_degree,
           agg.merge_delta_auto_rows, agg.transient,
           agg.fold_accumulates, agg.transform_may_alias,
           agg.jit_transform)
    per_agg = agg.__dict__.setdefault("_plan_cache", {})
    if key in per_agg:
        return per_agg[key]

    S = mesh_lib.num_shards(m)
    shard_leaf = lambda tree: jax.tree.map(lambda l: l[None], tree)
    unshard_leaf = lambda tree: jax.tree.map(lambda l: l[0], tree)
    sharded = NamedSharding(m, P(SHARD_AXIS))

    # Fresh [S, ...]-stacked local summaries, rebuilt at EVERY window
    # close (folds donate their input, so a shared locals0 object would
    # be consumed by the first fold that sees it). Jitted so the rebuild
    # is one cached on-device dispatch — the eager host-broadcast +
    # device_put version costs a full H2D per window, which at
    # merge_every=1 means per chunk.
    @partial(jax.jit, out_shardings=sharded)
    def locals0_fn():
        return jax.tree.map(
            lambda l: jnp.broadcast_to(l[None], (S,) + l.shape), agg.init()
        )

    # Fold state is DONATED (donate_argnums=0): the steady-state pipeline
    # re-dispatches the fold dozens of times per merge window, and without
    # donation every dispatch allocates a fresh full-capacity summary.
    # With it, XLA writes the new summary into the old one's buffers —
    # zero allocation after the first fold. The engine upholds the
    # donation contract by never reading a summary object after passing it
    # to a fold (locals are rebound by every fold call and rebuilt fresh
    # at each window close; see close_window). The jitlint GL006 rule
    # guards the same contract statically. The ONE plan shape where a
    # summary ESCAPES to the caller is the accumulate plan without a
    # transform: close_window yields the live fold state itself, and a
    # donated next fold would delete the consumer's held emission out
    # from under it — donation stays off exactly there.
    accum_plan = agg.fold_accumulates and not agg.transient and S == 1
    donate = () if (
        accum_plan and (agg.transform is None or agg.transform_may_alias)
    ) else (0,)
    if S == 1:
        # Single-shard specialization: the shard_map + collective plumbing
        # is identity at S=1 and only adds dispatch/layout overhead.
        locals0_fn = jax.jit(agg.init)  # noqa: F811

        fold_step = jax.jit(agg.fold, donate_argnums=donate)
        merge_locals = jax.jit(lambda s: s)

        @partial(jax.jit, donate_argnums=donate)
        def fold_many(s, stacked_chunk):
            # K chunks in one dispatch: scan the fold over the stacked
            # leading axis. Dispatch round-trips (~15ms each on a tunneled
            # device) amortize K-fold.
            def step(acc, ck):
                return agg.fold(acc, ck), None

            s, _ = jax.lax.scan(step, s, stacked_chunk)
            return s

        if agg.fold_compressed is not None:
            fold_codec = jax.jit(agg.fold_compressed, donate_argnums=donate)
        else:
            fold_codec = None
    else:
        @partial(jax.jit, out_shardings=sharded, donate_argnums=0)
        def fold_step(locals_, chunk):
            # Split fused into the same program as the fold: one dispatch
            # per chunk (dispatch round-trips dominate on a tunneled device).
            chunk_split = partition.split_chunk(chunk, S)

            def body(loc, ck):
                s = unshard_leaf(loc)
                c = EdgeChunk(*(x[0] for x in ck))
                return shard_leaf(agg.fold(s, c))

            return mesh_lib.shard_map_fn(
                m, body, in_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
                out_specs=P(SHARD_AXIS),
            )(locals_, chunk_split)

        @jax.jit
        def merge_locals(locals_):
            def body(loc):
                s = unshard_leaf(loc)
                if agg.merge_degree is not None:
                    g = collectives.hierarchical_merge(
                        agg.combine, s, S, min(agg.merge_degree, S)
                    )
                elif agg.merge_stacked is not None:
                    g = collectives.gather_merge(agg.merge_stacked, s)
                else:
                    g = collectives.butterfly_merge(agg.combine, s, S)
                return shard_leaf(g)

            merged = mesh_lib.shard_map_fn(
                m, body, in_specs=(P(SHARD_AXIS),), out_specs=P(SHARD_AXIS),
            )(locals_)
            # All shards hold the identical global merge; take shard 0.
            return unshard_leaf(merged)

        @partial(jax.jit, out_shardings=sharded, donate_argnums=0)
        def fold_many(locals_, stacked_chunk):
            # K chunks in one dispatch on the sharded raw path (VERDICT r2
            # item 7): each chunk of the host-stacked [K, C] batch splits
            # across shards ([S, K, C/S]) and the per-shard fold scans the
            # batch inside a single shard_map program — the same K-fold
            # dispatch amortization as the S=1 fold_many. The split itself
            # is fold_step's split_chunk, vmapped over the batch axis.
            split = jax.vmap(
                lambda c: partition.split_chunk(c, S)
            )(stacked_chunk)
            chunk_split = EdgeChunk(*(x.swapaxes(0, 1) for x in split))

            def body(loc, ckb):
                s = unshard_leaf(loc)

                def step(acc, ck):
                    return agg.fold(acc, ck), None

                s, _ = jax.lax.scan(
                    step, s, EdgeChunk(*(x[0] for x in ckb))
                )
                return shard_leaf(s)

            return mesh_lib.shard_map_fn(
                m, body, in_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
                out_specs=P(SHARD_AXIS),
            )(locals_, chunk_split)

        if agg.fold_compressed is not None:
            # Codec payloads are data-parallel over the chunk axis: a batch
            # of K payloads arrives as [S, K/S, ...]-sharded leaves and each
            # device folds its K/S payloads into its local summary.
            @partial(jax.jit, out_shardings=sharded, donate_argnums=0)
            def fold_codec(locals_, payload):
                def body(loc, pl):
                    s = unshard_leaf(loc)
                    p = jax.tree.map(lambda x: x[0], pl)
                    return shard_leaf(agg.fold_compressed(s, p))

                return mesh_lib.shard_map_fn(
                    m, body, in_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
                    out_specs=P(SHARD_AXIS),
                )(locals_, payload)
        else:
            fold_codec = None

    @jax.jit
    def merger_step(window_summary, global_summary):
        # The parallelism-1 Merger (M/SummaryAggregation.java:107-119):
        # incremental non-blocking global combine.
        return agg.combine(window_summary, global_summary)

    # Dirty-delta merge programs (merge_mode="delta"/"auto", S > 1 plans
    # that supply merge_delta): one tiny count program sizing the gather
    # bucket, and one merge program per bucket (a bounded pow-2 ladder —
    # O(log capacity) distinct programs per stream). The merge fuses the
    # cross-shard merge AND the Merger combine: it applies every shard's
    # gathered dirty rows directly to the carried global summary, so the
    # per-window merge cost is ∝ hooks, not ∝ capacity.
    delta_count_fn = None
    merge_delta_for = None
    if agg.merge_mode not in ("replicated", "delta", "auto"):
        # Fail loudly like every other plan knob: a typo'd mode on a
        # hand-built SummaryAggregation would otherwise silently run the
        # capacity-proportional replicated merge — the exact wall the
        # delta path exists to avoid. (Library plans validate earlier in
        # resolve_merge_mode; the engine is a public path too.)
        raise ValueError(
            f"plan {agg.name!r}: merge_mode must be 'replicated', "
            f"'delta' or 'auto', got {agg.merge_mode!r}"
        )
    if S > 1 and agg.merge_mode == "delta" and agg.merge_delta is None:
        raise ValueError(
            f"plan {agg.name!r} sets merge_mode='delta' but supplies no "
            "merge_delta — the delta merge is summary-specific and must "
            "come from the plan (see SummaryAggregation.merge_delta); "
            "use merge_mode='replicated' for plans without one"
        )
    if (S > 1 and agg.merge_delta is not None
            and (agg.merge_mode == "delta"
                 or (agg.merge_mode == "auto"
                     and agg.merge_delta_auto_rows is not None
                     and S * DELTA_MERGE_MIN_BUCKET
                     <= agg.merge_delta_auto_rows))):
        if agg.merge_dirty_count is None:
            raise ValueError(
                f"plan {agg.name!r} supplies merge_delta without "
                "merge_dirty_count — the engine sizes the delta gather "
                "bucket from the measured count; supply both or neither"
            )

        @jax.jit
        def delta_count_fn(locals_):  # noqa: F811
            def body(loc):
                return agg.merge_dirty_count(unshard_leaf(loc))[None]

            return mesh_lib.shard_map_fn(
                m, body, in_specs=(P(SHARD_AXIS),),
                out_specs=P(SHARD_AXIS),
            )(locals_)

        _delta_cache: dict = {}

        def merge_delta_for(bucket):  # noqa: F811
            fn = _delta_cache.get(bucket)
            if fn is None:
                @jax.jit
                def fn(locals_, global_summary):
                    def body(loc, g):
                        merged = agg.merge_delta(
                            g, unshard_leaf(loc), bucket
                        )
                        return shard_leaf(merged)

                    out = mesh_lib.shard_map_fn(
                        m, body, in_specs=(P(SHARD_AXIS), P()),
                        out_specs=P(SHARD_AXIS),
                    )(locals_, global_summary)
                    # Every shard applied the identical gathered delta to
                    # the identical base; take shard 0 (same convention
                    # as merge_locals).
                    return unshard_leaf(out)

                _delta_cache[bucket] = fn
            return fn

    # transform runs jitted by default: an eager lax.while_loop (e.g. the CC
    # label pointer-jump) re-dispatches per call and dominates the window
    # cost. Host-side transforms set jit_transform=False.
    if agg.transform is None:
        transform_fn = None
    elif agg.jit_transform:
        transform_fn = jax.jit(agg.transform)
    else:
        transform_fn = agg.transform

    # The cadenced path flatten, jitted but NOT donating: at checkpoint
    # cadence the pre-flatten summary may still be held by a consumer
    # (the accumulate plan yields the live state), so the old buffers
    # must survive the call.
    flatten_fn = jax.jit(agg.flatten) if agg.flatten is not None else None

    plan = (fold_step, merge_locals, merger_step, locals0_fn,
            transform_fn, fold_many, fold_codec, delta_count_fn,
            merge_delta_for, flatten_fn)
    per_agg[key] = plan
    return plan


class TenantPlan(NamedTuple):
    """Compiled vmapped physical plan for one tenant tier (see
    ``engine/tenants.py``): every function operates on summaries STACKED
    along a leading tenant axis of static width ``lanes``, so one donated
    dispatch advances every lane of the tier."""

    init: Callable[[], Summary]  # -> [lanes, ...]-stacked fresh summaries
    fold: Callable[..., Summary]  # (stacked, stacked_chunk, active) -> stacked
    merger: Callable[[Summary, Summary], Summary]  # vmapped combine
    transform: Callable[[Summary], Any] | None  # vmapped transform
    snapshot: Callable[[Summary], Any]  # query-safe copy (never aliases)
    flatten: Callable[[Summary], Summary] | None  # vmapped path flatten
    lanes: int
    # Vmapped compressed fold for codec tiers: (stacked, stacked_payload,
    # active) -> stacked, each lane folding its own pre-compressed
    # [1, ...]-batched payload (None for plans without fold_compressed).
    fold_codec: Callable[..., Summary] | None = None


def _compiled_tenant_plan(agg: SummaryAggregation, lanes: int,
                          mesh=None) -> TenantPlan:
    """Build (and memoize on the aggregation instance, like
    :func:`_compiled_plan`) the vmapped tenant-tier plan.

    The tenant axis replaces the shard axis as the data-parallel axis:
    ``fold``/``combine``/``transform`` are ``jax.vmap``-ed over a leading
    axis of ``lanes`` tenants, and the fold DONATES the stacked state —
    one dispatch, zero steady-state allocation, N tenants advanced.
    ``active`` masks no-op lanes (a tenant with no pending chunk keeps
    its summary bit-unchanged via a per-lane select), so stragglers
    never stall the batch. Tiers share one compiled program per
    ``lanes`` width (widths grow by doubling, so a stream of admissions
    compiles O(log N) programs, not O(N)).

    With ``mesh`` spanning S > 1 devices and ``lanes % S == 0`` the
    TENANT axis itself is sharded across the mesh — the lanes are
    data-parallel with no cross-lane collectives, so XLA partitions the
    vmapped program for free.

    Plans whose codec is a STATEFUL ordered stacker (``stack_ordered``)
    are refused loudly: their id-assignment session consumes payloads in
    global stream order, which concurrent tenant lanes cannot provide.
    Plain codec plans (``host_compress``/``fold_compressed``, incl.
    ``requires_codec``) compile a vmapped ``fold_codec`` next to the raw
    fold — the compressed-tier dispatch path. Host-side transforms
    (``jit_transform=False``) are refused too — queries read device
    snapshots.
    """
    if lanes < 1:
        raise ValueError(f"lanes must be >= 1, got {lanes}")
    windowed_panes = getattr(agg, "windowed_panes", None)
    if windowed_panes is not None:
        raise ValueError(
            f"aggregation '{agg.name}' carries a pane ring "
            f"(windowed_panes={windowed_panes}): the ring's two-stack "
            "state and TTL session rebuilds are single-stream host "
            "structures the vmapped tenant lanes cannot share — run "
            "the windowed query as its own stream, or add the "
            "non-windowed builder variant as the tier plan"
        )
    if agg.stack_ordered:
        raise ValueError(
            f"aggregation '{agg.name}' uses an ordered stacker "
            "(stack_ordered: its codec session assigns compact ids in "
            "GLOBAL STREAM order — per-run host state no concurrent "
            "tenant lane order can reproduce); build the tier plan on a "
            "stateless codec (e.g. codec='sparse') or the raw fold "
            "(ingest_combine=False)"
        )
    if agg.requires_codec and agg.fold_compressed is None:
        raise ValueError(
            f"aggregation '{agg.name}' sets requires_codec but supplies "
            "no fold_compressed — the tier has no fold to compile"
        )
    if agg.transform is not None and not agg.jit_transform:
        raise ValueError(
            f"aggregation '{agg.name}' uses a host-side transform "
            "(jit_transform=False); tenant snapshots are device-resident "
            "vmapped transforms"
        )
    mesh_key = None
    sharding = None
    if mesh is not None and mesh_lib.num_shards(mesh) > 1:
        S = mesh_lib.num_shards(mesh)
        if lanes % S:
            raise ValueError(
                f"tenant lanes {lanes} must be a multiple of the "
                f"{S}-device mesh to shard the tenant axis"
            )
        mesh_key = (tuple(d.id for d in mesh.devices.flat), mesh.axis_names)
        sharding = NamedSharding(mesh, P(SHARD_AXIS))
    key = ("tenants", lanes, agg.fold_backend, agg.merge_mode, mesh_key)
    per_agg = agg.__dict__.setdefault("_plan_cache", {})
    if key in per_agg:
        return per_agg[key]

    jit_kw = {} if sharding is None else {"out_shardings": sharding}

    @partial(jax.jit, **jit_kw)
    def batch_init():
        return jax.tree.map(
            lambda l: jnp.broadcast_to(l[None], (lanes,) + l.shape),
            agg.init(),
        )

    def _lane_fold(s, chunk, active):
        # Masked no-op lane: the fold still runs (static shapes, one
        # program) but an inactive lane's summary is selected back
        # bit-unchanged — the fairness contract's "no-op masked lane".
        s2 = agg.fold(s, chunk)
        return jax.tree.map(
            lambda new, old: jnp.where(active, new, old), s2, s
        )

    # The tenant-axis donation: steady-state tenant folds write the new
    # stacked summary into the old one's buffers (same contract as the
    # single-stream fold_step — the engine rebinds the state on every
    # call and snapshots only through `snapshot`, which never aliases).
    batch_fold = jax.jit(jax.vmap(_lane_fold), donate_argnums=0, **jit_kw)

    batch_fold_codec = None
    if agg.fold_compressed is not None:
        def _lane_fold_codec(s, payload, active):
            # Each lane folds its own [1, ...]-batched compressed payload
            # (the engine's stacked-unit contract at K=1, so the very
            # same fold_compressed serves both paths); inactive lanes
            # select back bit-unchanged like the raw masked lane.
            s2 = agg.fold_compressed(s, payload)
            return jax.tree.map(
                lambda new, old: jnp.where(active, new, old), s2, s
            )

        batch_fold_codec = jax.jit(
            jax.vmap(_lane_fold_codec), donate_argnums=0, **jit_kw
        )

    batch_merger = jax.jit(jax.vmap(agg.combine), **jit_kw)

    batch_transform = (
        jax.jit(jax.vmap(agg.transform), **jit_kw)
        if agg.transform is not None else None
    )

    if batch_transform is not None:
        snapshot_fn = batch_transform
    else:
        # Query snapshots must never alias the live (donated-into-next-
        # fold) state buffers: jnp.copy dispatched EAGERLY is a real
        # device copy — a jitted identity could alias its input.
        def snapshot_fn(s):
            return jax.tree.map(jnp.copy, s)

    batch_flatten = (
        jax.jit(jax.vmap(agg.flatten), **jit_kw)
        if agg.flatten is not None else None
    )

    plan = TenantPlan(
        init=batch_init, fold=batch_fold, merger=batch_merger,
        transform=batch_transform, snapshot=snapshot_fn,
        flatten=batch_flatten, lanes=lanes, fold_codec=batch_fold_codec,
    )
    per_agg[key] = plan
    return plan


def run_aggregation(
    agg: SummaryAggregation,
    stream,
    mesh=None,
    merge_every: int | None = None,
    window_ms: int | None = None,
    checkpoint_path: str | None = None,
    checkpoint_every: int = 1,
    resume: bool = False,
    prefetch_depth: int | None = None,
    device_fields: tuple[str, ...] | None = None,
    host_precombine: Callable | None = None,
    fold_batch: int = 1,
    ingest_workers: int | None = None,
    codec_workers: int | None = None,
    h2d_depth: int | None = None,
    allowed_lateness: int = 0,
    timer=None,
    source_provider=None,
    queries=None,
    precompressed: bool = False,
    windowed: int | None = None,
    ttl_panes: int | None = None,
) -> SummaryStream:
    """Execute ``agg`` over ``stream`` — the TPU ``run()``.

    ``merge_every`` (chunks) or ``window_ms`` (timestamp-tumbling) sets the
    merge/emit cadence; default is merge_every=1 (a merge after every chunk,
    the closest analog of the reference's per-window emission).

    ``prefetch_depth`` chunks of host ingest (parse/densify/H2D) overlap
    device folds on a background thread; 0 disables. Default (None) is
    ``max(2, ingest_workers)`` so the worker pool stays fed; an EXPLICIT
    value is honored exactly — it is the caller's bound on in-flight
    staged units (host/device memory ∝ depth × unit size), and capping it
    below the worker count deliberately idles workers for memory.

    ``device_fields`` names chunk fields to device_put on the prefetch
    thread (e.g. ``("src", "dst", "valid")`` for CC): the H2D of exactly
    the fields the fold reads then overlaps compute, while unused fields
    stay host-side (jit prunes dead args, so they are never transferred).

    ``host_precombine(chunk) -> chunk`` runs on the prefetch thread before
    staging — an ingest-side partial pre-aggregation (e.g.
    ``cc_host_precombine`` reduces each chunk to its spanning forest).
    Ignored in window mode: a pre-combiner may not preserve per-edge
    timestamps.

    ``checkpoint_path`` snapshots the global summary + stream position every
    ``checkpoint_every`` closed windows (the Merger's ListCheckpointed analog,
    M/SummaryAggregation.java:127-135); ``resume=True`` reloads it and skips
    the already-folded chunks.

    ``fold_batch`` groups up to that many chunks into one device dispatch
    (clamped to a divisor of ``merge_every``): the fold scans the stacked
    batch in a single program, amortizing per-dispatch latency. When the
    aggregation defines an ingest codec (``host_compress``/
    ``fold_compressed``), batches are compressed payload stacks instead of
    raw chunks — the high-throughput path on a bandwidth-limited
    host->device link. Sharded-codec floor: with a codec on S > 1 shards
    the payload batch axis is split across devices, so the effective batch
    is promoted to a multiple of S — in particular ``fold_batch=1`` with
    ``merge_every % S == 0`` silently becomes ``batch=S`` (S stacked
    payloads per dispatch: more per-dispatch host memory/latency than
    requested, but the only aligned batching).

    ``timer`` (a ``utils.metrics.StageTimer``) accumulates per-stage BUSY
    time: ``ingest_compress`` (codec worker pool), ``h2d`` (the dedicated
    transfer thread), ``fold_dispatch`` / ``merge_emit`` (consumer).
    Stages overlap, so their sum can exceed — and with a healthy pipeline
    total wall SHOULD undercut — the serial sum. Also exposed as
    ``stream.timer``.

    **Pipelined executor** (merge_every mode): the fold path runs as a
    three-stage pipeline —

      produce → [K codec workers: host compress]
              → [1 H2D thread: device_put chunk i+1 while chunk i folds]
              → [consumer: async fold dispatch]

    ``codec_workers`` (alias of ``ingest_workers``; passing both raises)
    sizes the compress pool; ``h2d_depth`` bounds the transferred units
    resident on device ahead of the fold (default 2 — classic double
    buffering; 0 stages transfers inline on the consumer). Fold state is
    donated (``donate_argnums``), so steady-state folds reallocate
    nothing, and the consumer synchronizes ONCE per merge window (the
    ``merge_emit`` block) instead of per chunk.

    **Observability**: install an ``obs.SpanTracer`` (``with
    gelly_tpu.obs.install(SpanTracer()): ...``) around the run and every
    pipeline unit records produce → compress (worker) → H2D (buffer
    slot) → fold spans with queue-depth and payload-size attribution;
    window closes, checkpoints, retries and injected faults land as
    spans/instant events; and a periodic heartbeat line reports eps,
    queue depths and the last-retired position. Export with
    ``obs.write_chrome_trace`` (Perfetto-loadable). Without a tracer the
    unit path performs ZERO extra observability work. Counters
    (units/chunks folded, windows closed, checkpoint bytes) land on
    ``obs.get_bus()`` either way.

    **Sharded source readers** (``source_provider``): pass a
    ``gelly_tpu.ingest.ShardedEdgeSource`` (or ``True`` to use
    ``stream.source``) and the produce-compress leg is replaced
    entirely — S reader lanes each parse their own byte range of the
    edge file AND run the compress stage on their own thread, handing
    COMPLETED units to the H2D/fold stages in the provider's
    deterministic merge order. There is no shared produce iterator
    left: a trace capture shows one ``compress/gelly-reader_<s>`` track
    per lane instead of a serial produce span train. Provider mode is
    merge_every-only (sharded ranges carry no global arrival order) and
    refuses ordered stackers (``stack_ordered`` codecs assign ids in
    global stream order, which sharded lanes cannot provide). Resume
    composes with the last-retired-chunk rule below: the provider maps
    the single recorded position onto per-shard seek offsets.

    **Fused multi-query execution** (``queries=[...]``): pass a list of
    query specs instead of ``agg`` and the engine fuses them into ONE
    plan (``engine/multiquery.py``): each chunk is produced, staged and
    transferred H2D exactly once and every query's fold runs inside the
    same compiled program — one fold dispatch per chunk regardless of
    Q. The returned stream is a
    :class:`~gelly_tpu.engine.multiquery.MultiQueryStream` (emission
    dicts keyed by query name + live per-query ``snapshot`` reads with
    a one-window staleness bound). Merge-every mode only; see the
    multiquery module docs for fusion eligibility.

    **Pre-compressed payload streams** (``precompressed=True``): the
    stream yields per-chunk COMPRESSED payloads (the plan's
    ``host_compress`` output — e.g. wire ``DATA_COMPRESSED`` frames a
    client compressed before send) instead of chunks. The staging
    workers then skip ``host_compress`` entirely: each unit is stacked
    (``stack_payloads``) and transferred as received, so a traced run
    shows ZERO ``compress`` spans — the per-unit staging work lands on
    a ``stack`` span/timer stage instead. The shared-compression-plane
    contract: a chunk is compressed once, at the producer, and every
    downstream consumer folds the compressed payload directly.
    Requires a plan whose codec can engage here (``host_compress`` +
    ``fold_compressed``, and a batch the mesh can align — the same
    rules as ``requires_codec``); merge_every mode only (payloads
    carry no per-edge timestamps), and ``host_precombine`` /
    ``source_provider`` are chunk-path knobs it refuses. The
    last-retired-chunk checkpoint rule counts payload units exactly
    like chunks, so exactly-once resume composes unchanged.

    **Sliding pane-ring windows** (``windowed=W``): the emission covers
    only the last W *panes* instead of the whole stream, where one pane
    is one merge window (``merge_every`` chunks — the pane size knob).
    Pane summaries live in a ring and the W-pane window is answered by
    a two-stack suffix aggregation (the FOO/DABA shape), so a pane
    close costs O(1) amortized ``combine`` dispatches — never a W-pane
    re-merge and never a replay — and the per-close cost scales with
    the PANE size, not the window length. ``ttl_panes=T`` (T >= W,
    compact-id plans only) adds per-vertex decay: each compact-id slot
    carries a last-seen pane stamp, and slots idle for T panes are
    evicted through the plan's ``windowed_evict`` hook at a pane
    boundary (the ``CompactIdSession`` rebuild), so steady-state device
    capacity is bounded by the ACTIVE vertex set, not the stream
    history. TTL requires a quiesced pipeline (``prefetch_depth=0,
    h2d_depth=0``): the session rebuild renumbers compact ids, which is
    only sound with no staged-but-unfolded assignments in flight. The
    returned stream is a :class:`WindowedStream`; ``snapshot()`` reads
    the latest ``{"window", "labels"}`` emission under a lock while the
    stream advances (one-pane staleness — the same contract as
    tenant/multiquery snapshots). Checkpoints snapshot the ring, the
    persistent id map and the TTL stamps under the same single recorded
    position (pane boundaries are the only checkpoint points in
    windowed mode), so exactly-once resume covers the ring + pane index
    bit-identically.

    **Exactly-once resume — the last-retired-chunk rule**: the recorded
    checkpoint position counts only chunks whose fold was *dispatched*
    (retired from the pipeline); units still in the compress/H2D double
    buffers are NOT counted. The snapshot's device_get barrier guarantees
    every retired fold is in the snapshot, so resume re-reads exactly the
    un-retired suffix — bit-identical to an uninterrupted run even when
    the crash lands with chunks in flight (stateful codec sessions are
    rebuilt from the restored summary via ``on_resume``, dropping any
    staged-but-unfolded assignments).
    """
    if queries is not None:
        # The fused multi-query entry point: compose the queries into
        # one MultiQueryPlan (engine/multiquery.py) so every question
        # rides ONE produce/compress/H2D leg and ONE fold dispatch per
        # chunk. The emission stream is wrapped in a MultiQueryStream
        # (live per-query snapshots) at the bottom of this function.
        if agg is not None:
            raise ValueError(
                "pass a single aggregation OR queries=[...], not both "
                "(queries are fused into one plan by engine.multiquery)"
            )
        from .multiquery import fuse

        agg = fuse(queries)
    if agg is None:
        raise ValueError("an aggregation is required (or pass queries=[...])")
    # Normalized QuerySpec tuple of a fused plan; None for plain plans.
    fused = getattr(agg, "queries", None) or None
    if merge_every is not None and window_ms is not None:
        raise ValueError("pass at most one of merge_every / window_ms")
    if allowed_lateness and window_ms is None:
        raise ValueError(
            "allowed_lateness requires window_ms (merge_every mode is "
            "count-based and does not reorder by timestamp)"
        )
    if merge_every is None and window_ms is None:
        merge_every = 1
    if agg.merge_degree is not None:
        d = agg.merge_degree
        if d <= 0 or (d & (d - 1)):
            raise ValueError(
                f"merge_degree must be a positive power of two, got {d}"
            )

    if source_provider is True:
        source_provider = getattr(stream, "source", None)
        if source_provider is None:
            raise ValueError(
                "source_provider=True needs a stream whose .source is a "
                "sharded provider (edge_stream_from_sharded_file); this "
                "stream has none"
            )
    if source_provider is not None:
        if not hasattr(source_provider, "stage_units"):
            raise ValueError(
                f"source_provider {type(source_provider).__name__} does "
                "not implement stage_units(stage_fn, batch, start, depth, "
                "cancel, gauge) — pass a gelly_tpu.ingest."
                "ShardedEdgeSource or an object with that protocol"
            )
        if window_ms is not None:
            raise ValueError(
                "source_provider is merge_every-only: sharded reader "
                "lanes have no global arrival order, so timestamp-"
                "tumbling windows cannot be formed from them"
            )
        if agg.stack_ordered:
            raise ValueError(
                f"aggregation '{agg.name}' uses an ordered stacker "
                "(stack_ordered codec session assigning ids in global "
                "stream order); sharded reader lanes compress "
                "concurrently with no global order — use the "
                "single-iterator path or a stateless codec"
            )
        if codec_workers is not None or ingest_workers is not None:
            raise ValueError(
                "codec_workers/ingest_workers size the prefetch_map "
                "compress pool, which a source_provider replaces "
                "entirely — the provider's shard count IS the lane "
                "count (e.g. ShardedEdgeSource(shards=...)); drop the "
                "worker knob or the provider"
            )
    if codec_workers is not None:
        if ingest_workers is not None:
            raise ValueError(
                "pass codec_workers or ingest_workers, not both (they are "
                "the same knob; codec_workers is the executor-facing name)"
            )
        ingest_workers = codec_workers
    if h2d_depth is None:
        h2d_depth = 2  # double buffer: chunk i+1 transfers while i folds
    if h2d_depth < 0:
        raise ValueError(f"h2d_depth must be >= 0, got {h2d_depth}")
    if ingest_workers is None:
        # One codec worker per AVAILABLE core (affinity/cgroup-aware, not
        # installed count): the native combiners release the GIL, so
        # staging units scale with cores — each worker owns whole units
        # (chunks are never split across workers), so per-worker combiner
        # hash tables stay private and there is no cross-worker eviction
        # thrash. On a single-core host this degenerates to one worker
        # (two workers there evict each other's tens-of-MB working sets
        # and run ~2-4x slower than one). Capped at 8: staged units hold
        # host payloads plus H2D device buffers, so an uncapped default
        # would scale peak staging memory linearly with core count on
        # large hosts — callers wanting more pass ingest_workers
        # explicitly (the explicit value is honored unbounded).
        ingest_workers = min(available_cores(), 8)
    if prefetch_depth is None:
        # Defaults track the (already-capped) worker count; an EXPLICIT
        # ingest_workers above the default cap gets the matching depth —
        # capping here too would permanently idle the extra workers.
        prefetch_depth = max(2, ingest_workers)

    # Pane-ring eligibility (PC4xx refusal matrix): the knobs a pane
    # ring composes with are exactly the merge_every pipeline's — every
    # incompatible axis is refused loudly here, never silently ignored.
    if windowed is None:
        windowed = getattr(agg, "windowed_panes", None)
    if ttl_panes is None:
        ttl_panes = getattr(agg, "windowed_ttl_panes", None)
    windowed_evict = getattr(agg, "windowed_evict", None)
    win_touched = getattr(agg, "windowed_touched", None)
    win_persist_init = getattr(agg, "windowed_persist_init", None)
    win_persist_update = getattr(agg, "windowed_persist_update", None)
    win_query_fixup = getattr(agg, "windowed_query_fixup", None)
    win_on_resume = getattr(agg, "on_resume_windowed", None)
    if windowed is not None:
        windowed = int(windowed)
        if windowed < 1:
            raise ValueError(f"windowed must be >= 1 pane, got {windowed}")
        if window_ms is not None:
            raise ValueError(
                "windowed panes ride the merge_every cadence (one pane "
                "per merge window, merge_every chunks each); event-time "
                "window_ms is a different cadence axis — size the pane "
                "with merge_every instead"
            )
        if fused:
            raise ValueError(
                f"fused plan '{agg.name}' cannot carry a pane ring: the "
                "ring combines ONE plan's pane summaries, and per-query "
                "cadences (QuerySpec.every) would desynchronize the "
                "shared pane boundary — run the windowed query as its "
                "own stream"
            )
        if agg.transient:
            raise ValueError(
                f"aggregation '{agg.name}' is transient (emit-and-reset "
                "Merger): its windows are already independent, so a "
                "pane ring over them has nothing to combine — drop "
                "windowed= or use a non-transient plan"
            )
        if source_provider is not None:
            raise ValueError(
                "windowed panes are single-iterator only: sharded "
                "reader lanes retire units in provider merge order, "
                "and the pane boundary (merge_every chunks) must land "
                "on the exactly-once stream order the ring checkpoints"
            )
        if precompressed:
            raise ValueError(
                "windowed panes refuse precompressed payload streams "
                "for now: pre-grouped STACKED units may straddle a "
                "pane boundary, which would fold one frame into two "
                "panes — feed raw chunks (the engine compresses "
                "per-pane)"
            )
        if agg.merge_mode == "delta" or agg.merge_delta is not None:
            raise ValueError(
                f"aggregation '{agg.name}' supplies a dirty-delta merge "
                "(merge_mode/merge_delta): the delta path folds dirty "
                "rows into a CARRIED global summary, but a pane ring "
                "retires panes — the two memory models are exclusive; "
                "use the windowed builder variant (merge_delta=None)"
            )
    if ttl_panes is not None:
        ttl_panes = int(ttl_panes)
        if windowed is None:
            raise ValueError(
                "ttl_panes requires windowed=W: TTL stamps are "
                "last-seen PANE indices, and eviction runs at pane "
                "boundaries — there is no pane clock without a ring"
            )
        if ttl_panes < windowed:
            raise ValueError(
                f"ttl_panes={ttl_panes} < windowed={windowed}: a slot "
                "must outlive the ring (T >= W) so an evicted id is "
                "guaranteed untouched in every live pane — otherwise "
                "eviction would rewrite panes that still reference it"
            )
        if windowed_evict is None or win_touched is None:
            raise ValueError(
                f"aggregation '{agg.name}' has no TTL eviction hooks "
                "(windowed_evict + windowed_touched): per-vertex decay "
                "needs a compact-id plan that can renumber its session "
                "— build one with connected_components(compact=..., "
                "windowed=W, ttl_panes=T)"
            )
        if prefetch_depth != 0 or h2d_depth != 0:
            raise ValueError(
                "ttl_panes needs a quiesced pipeline: pass "
                "prefetch_depth=0 and h2d_depth=0 so no compact-id "
                "assignment is staged but unfolded when the session "
                "renumbers at a pane boundary (in-flight payloads "
                "would still carry the OLD ids)"
            )
    m = mesh if mesh is not None else mesh_lib.make_mesh()
    S = mesh_lib.num_shards(m)
    if fused:
        if window_ms is not None:
            raise ValueError(
                f"fused plan '{agg.name}' is merge_every-only: per-query "
                "cadences (QuerySpec.every) count chunks, and event-time "
                "windows cannot mask the shared fused fold per query"
            )
        if host_precombine is not None:
            raise ValueError(
                "host_precombine rewrites the shared chunk for ONE "
                "query's benefit; a fused plan folds EVERY query from "
                "the same chunk — drop it (fold the pre-combine into "
                "that query's own fold instead)"
            )
        if S > 1 and any(not q.accum or q.every != 1 for q in fused):
            raise ValueError(
                f"fused plan '{agg.name}' carries a non-accumulating "
                "query (or a per-query merge window > 1): its in-fold "
                "merges are per-partition, so the fused plan is "
                f"single-shard — run on a 1-device mesh (S={S} here); "
                "scale out by sharding the TENANT axis via "
                "MultiTenantEngine(mesh=...) instead"
            )
    plan = _compiled_plan(agg, m)
    (fold_step, merge_locals, merger_step, locals0_fn,
     transform_fn, fold_many, fold_codec, delta_count_fn,
     merge_delta_for, flatten_fn) = plan

    if timer is None:
        from ..utils.metrics import StageTimer

        timer = StageTimer()

    # Window-mode codec (VERDICT r3 item 8; mesh form r4 item 5): the
    # tumbling iterator masks each chunk to ONE window before the fold,
    # so compressing the masked chunk needs no per-edge timestamps on the
    # wire — the payload is implicitly scoped to its window. On S > 1
    # shards the masked chunk splits into S host slices whose payloads
    # ride the same [S, 1, ...] batch-axis split as merge_every staging
    # (the reference's full-parallelism per-window fold,
    # M/SummaryBulkAggregation.java:78-83).
    use_codec = (
        agg.host_compress is not None
        and agg.fold_compressed is not None
    )
    # Effective batch: a divisor of merge_every so window boundaries align
    # with batch boundaries; on a sharded codec plan, also a multiple of S
    # (the payload batch axis is split across devices).
    batch = 1
    if window_ms is None:
        batch = max(1, min(fold_batch, merge_every))
        while merge_every % batch:
            batch -= 1
        if use_codec and S > 1:
            if batch % S:
                batch = S if merge_every % S == 0 else 1
            if batch % S:
                use_codec = False  # no aligned batching possible

    # The precompressed checks come FIRST: a stack_ordered plan must be
    # named for its ordered session, not for a batch-alignment detail.
    if precompressed:
        if window_ms is not None:
            raise ValueError(
                "precompressed=True is merge_every-only: codec payloads "
                "carry no per-edge timestamps to form event-time "
                "windows from"
            )
        if host_precombine is not None:
            raise ValueError(
                "host_precombine rewrites raw chunks; a precompressed "
                "stream carries codec payloads the producer already "
                "reduced — drop one of the two"
            )
        if source_provider is not None:
            raise ValueError(
                "source_provider parses raw edge files; a precompressed "
                "stream already carries codec payloads — drop one of "
                "the two"
            )
        if agg.stack_ordered:
            raise ValueError(
                f"aggregation '{agg.name}' uses an ordered stacker "
                "(stack_ordered): its codec session assigns compact "
                "ids in global stream order on THIS side, and its "
                "per-chunk host_compress ships raw edge views — a "
                "producer cannot meaningfully pre-compress for it; "
                "use a stateless codec (e.g. codec='sparse') on the "
                "wire"
            )
        if not use_codec:
            raise ValueError(
                f"precompressed=True needs a codec-capable plan: "
                f"'{agg.name}' must supply host_compress + "
                "fold_compressed (and the payload batch must align "
                f"with the {S}-shard mesh) so the pre-compressed "
                "payloads have a fold to land in"
            )
    if agg.requires_codec and not use_codec:
        raise ValueError(
            f"aggregation '{agg.name}' folds only through its ingest codec, "
            "but the codec cannot engage here: "
            f"merge_every={merge_every} cannot align a payload "
            f"batch with the {S}-shard mesh (make merge_every a "
            "multiple of the shard count)"
        )

    stats = {"late_edges": 0, "windows_closed": 0, "chunks": 0,
             "merge_modes": {"delta": 0, "replicated": 0}}

    # Queryable epoch snapshot holder (windowed mode): the latest
    # {window, labels} emission, readable under a lock while the stream
    # advances — published at every pane close, so a reader is at most
    # one pane stale (the tenant/multiquery snapshot contract).
    win_holder = None
    if windowed is not None:
        win_holder = {"lock": threading.Lock(), "val": None}

    # The accumulate plan (see SummaryAggregation.fold_accumulates): one
    # running summary, no per-window Merger combine. A pane ring opts
    # out: panes must fold from FRESH locals so each pane summary covers
    # exactly its own merge window (the ring supplies the accumulation).
    accum = (agg.fold_accumulates and not agg.transient and S == 1
             and windowed is None)

    def gen():
        if agg.on_run_start is not None:
            agg.on_run_start()
        # Observability bindings, resolved ONCE per run: `tracer` is None
        # unless an obs.SpanTracer is installed, and every span site below
        # is guarded by that None check — the disabled unit path performs
        # zero extra allocations (not even a clock read). The bus is
        # always on; it is only touched at unit/window cadence.
        tracer = obs_tracing.active_tracer()
        bus = obs_bus.get_bus()
        # Serving-plane telemetry (histograms + e2e watermarks), bound
        # ONCE per run under the same zero-cost-when-disabled contract
        # as the tracer: `telemetry` is False (and `wm` is None) unless
        # a tracer is installed or obs.bus.recording() is on, and every
        # recording site below is guarded by it — the disabled unit
        # path performs no histogram work, not even a clock read.
        telemetry = obs_bus.telemetry_on()
        wm = bus.watermarks if telemetry else None
        # Sharded-provider unit seqs are lane-interleaved
        # (``local_unit * shards + shard``, resume offset baked into the
        # lane starts — readers.stage_units), so ``skip_until + seq *
        # batch`` does NOT map onto consumption positions there: stamps
        # would land above the positions retire_fold/retire_durable ever
        # reach and read as permanent backlog. Provider-path stamps draw
        # dense positions from this allocator instead (staging order ≈
        # consumption order within the prefetch depth; every allocated
        # position is < total chunks, so all stamps retire).
        wm_alloc = None
        if wm is not None and source_provider is not None:
            _wm_lock = threading.Lock()
            _wm_next = [0]

            def wm_alloc() -> int:
                # skip_until is read at call time: it is final (resume
                # position loaded) before any unit is staged.
                with _wm_lock:
                    pos = skip_until + _wm_next[0]
                    _wm_next[0] += 1
                    return pos

        staged_hw = 0  # staged-depth high-water since the last beat
        # Per-query span attribution for fused plans: every fold span
        # names the queries riding the dispatch (the MultiQueryStream
        # wrapper adds the per-query window tracks).
        fold_attrs = (
            {"queries": ",".join(q.name for q in fused)} if fused else {}
        )
        hb = None
        meter = None
        if tracer is not None:
            from ..utils.metrics import ThroughputMeter

            meter = ThroughputMeter()
            if tracer.heartbeat_every_s is not None:
                from ..obs.heartbeat import Heartbeat

                hb = Heartbeat(tracer.heartbeat_every_s)
        # Ordered-wait baseline for this run (the codec session resets in
        # on_run_start, but sample rather than assume zero): the delta to
        # teardown is reclassified ingest_compress -> codec_wait.
        wait0 = (
            agg.ordered_wait_s() if agg.ordered_wait_s is not None else 0.0
        )
        # Fresh locals per run AND per window (never a shared ``locals0``
        # object): folds donate their summary argument, so a reused
        # initial summary would be consumed by the first fold that sees
        # it and poison every later window.
        locals_ = locals0_fn()
        global_summary = agg.init()
        current_window = None
        dirty = False  # locals hold edges not yet merged into a window result
        chunks_in_window = 0
        chunks_consumed = 0
        skip_until = 0
        windows_closed = 0
        last_ckpt_windows = 0

        # Pane-ring state (windowed=W): a ring of pane summaries
        # answered by two-stack suffix aggregation (core/windows.py),
        # plus the compact-plan sidecars — the persistent id map
        # (superset of every live pane's assignments) and the per-slot
        # TTL last-seen stamps.
        ring = None
        persist_vof = None
        last_seen = None
        if windowed is not None:
            from ..core.windows import PaneRing

            ring = PaneRing(
                windowed, merger_step,
                on_combine=lambda n: bus.inc(
                    "windows.combine_dispatches", n),
            )
            if win_persist_init is not None:
                persist_vof = win_persist_init()
            if ttl_panes is not None:
                last_seen = np.zeros(
                    int(persist_vof.shape[0]), dtype=np.int64
                )

        def _win_like():
            # Static checkpoint template: [W, ...] stacked pane leaves
            # (live panes padded with init panes at save time), so the
            # on-disk shape never depends on ring occupancy.
            panes = jax.tree.map(
                lambda l: jnp.zeros((windowed,) + l.shape, l.dtype),
                agg.init(),
            )
            like = {"panes": panes}
            if persist_vof is not None:
                like["persist"] = jnp.zeros_like(persist_vof)
            if last_seen is not None:
                like["last_seen"] = jnp.zeros(last_seen.shape, jnp.int64)
            return like

        lat_handle: dict = {}
        lat_state = None
        if resume:
            if not checkpoint_path:
                raise ValueError("resume=True requires checkpoint_path")
            from .checkpoint import load_checkpoint

            if windowed is not None:
                snap_in, skip_until, meta_in = load_checkpoint(
                    checkpoint_path, like=_win_like()
                )
                snap_in = jax.tree.map(jnp.asarray, snap_in)
                live_n = int(meta_in.get("ring_live", 0))
                panes = [
                    jax.tree.map(lambda l, i=i: l[i], snap_in["panes"])
                    for i in range(live_n)
                ]
                current_window = meta_in.get("current_window")
                windows_closed = last_ckpt_windows = meta_in.get(
                    "windows", 0)
                ring.reload(panes, windows_closed)
                if persist_vof is not None:
                    persist_vof = snap_in["persist"]
                if last_seen is not None:
                    last_seen = np.asarray(snap_in["last_seen"]).copy()
                if win_on_resume is not None:
                    # Rebuild the compact-id session from the PERSISTENT
                    # map — a superset of every live pane's assignments —
                    # never from any single pane (panes only record
                    # FIRST-seen rows).
                    win_on_resume(np.asarray(persist_vof))
            else:
                global_summary, skip_until, meta_in = load_checkpoint(
                    checkpoint_path, like=global_summary
                )
                global_summary = jax.tree.map(jnp.asarray, global_summary)
                if agg.on_resume is not None:
                    agg.on_resume(global_summary)
                current_window = meta_in.get("current_window")
                windows_closed = last_ckpt_windows = meta_in.get(
                    "windows", 0)
                if accum:
                    # The running summary IS the restored global: folds
                    # resume into it directly.
                    locals_ = global_summary
            if allowed_lateness:
                import os as _os

                # Position-stamped sidecar names make the pair crash-safe:
                # the sidecar for position P is written BEFORE the main
                # file advances to P, and sidecars for older positions are
                # pruned only AFTER the main os.replace succeeds — so
                # whichever position the main file holds, its matching
                # sidecar is on disk. The unstamped name is the legacy
                # (pre-stamping) format, still position-checked.
                side = f"{checkpoint_path}.lateness.{skip_until}"
                if not _os.path.exists(side):
                    side = checkpoint_path + ".lateness"
                if _os.path.exists(side):
                    flat, side_pos, side_meta = load_checkpoint(side)
                    if side_pos != skip_until:
                        raise ValueError(
                            f"lateness sidecar position {side_pos} does "
                            f"not match checkpoint position {skip_until} "
                            "(crash between the paired writes?) — the "
                            "reorder buffer cannot be restored "
                            "consistently"
                        )
                    nf = len(EdgeChunk._fields)
                    lat_state = {
                        "wins": side_meta["wins"],
                        "chunks": [
                            EdgeChunk(*flat[i * nf:(i + 1) * nf])
                            for i in range(len(side_meta["wins"]))
                        ],
                        "closed_upto": side_meta["closed_upto"],
                        "max_ts": side_meta["max_ts"],
                    }

        if wm is not None:
            # (Re)seed the e2e ledger at the exactly-once resume point:
            # after a crash the low watermark re-seeds from the RESUMED
            # POSITION's re-read time — never the wall clock, so
            # backlog age cannot time-travel across a SIGKILL.
            wm.seed("stream", skip_until)

        def publish_watermarks():
            # Backlog-age low watermark after a window close / durable
            # point. Without a checkpoint path the window close IS the
            # run's retirement point (there is no later durability),
            # so the ledger drains there.
            if wm is None:
                return
            if not checkpoint_path:
                wm.retire_durable("stream", chunks_consumed, bus=bus,
                                  prefix="engine")
            bus.gauge("engine.backlog_age_s",
                      round(wm.backlog_age("stream"), 6))

        def close_window():
            nonlocal locals_, global_summary, windows_closed, dirty
            if accum:
                global_summary = locals_  # carried across windows, no reset
                dirty = False
                windows_closed += 1
                stats["windows_closed"] = windows_closed
                bus.inc("engine.windows_closed")
                if tracer is not None:
                    tracer.instant("window_close", window=windows_closed,
                                   mode="accumulate")
                return (
                    transform_fn(global_summary)
                    if transform_fn else global_summary
                )
            # The cross-shard merge boundary: seeded FaultPlans can
            # raise/hang here (a collective that dies mid-window), the
            # same way they drive the native/H2D/step/checkpoint paths.
            faults_mod.inject("collective")
            merged = None
            mode = "replicated"
            if delta_count_fn is not None:
                # Measured per-window decision: one scalar D2H (the count)
                # sizes the gather bucket; the delta program fuses the
                # cross-shard merge and the Merger combine, so the close
                # moves S * bucket dirty rows instead of S full summaries.
                count = int(np.max(np.asarray(delta_count_fn(locals_))))
                # The measured count IS hooks-since-last-merge — the
                # per-window visibility the delta-merge crossover lever
                # needs (ROADMAP: merge_delta_auto_rows is a host-side
                # heuristic pending a measured sweep).
                bus.gauge("engine.window_dirty_rows", count)
                bucket = max(DELTA_MERGE_MIN_BUCKET,
                             1 << max(0, count - 1).bit_length())
                limit = agg.merge_delta_auto_rows
                if agg.merge_mode == "delta" or (
                    limit is not None and S * bucket <= limit
                ):
                    merged = merge_delta_for(bucket)(locals_, global_summary)
                    stats["merge_modes"]["delta"] += 1
                    bus.inc("engine.dirty_rows_gathered", S * bucket)
                    mode = "delta"
            if merged is None:
                # Replicated path (the reference Merger shape): full
                # cross-shard merge, then combine into the global summary.
                # Counted here — not in the delta-decision else — so
                # replicated-only plans (merge_mode="replicated", S == 1,
                # no merge_delta) report their merges too.
                window_summary = merge_locals(locals_)
                merged = merger_step(window_summary, global_summary)
                stats["merge_modes"]["replicated"] += 1
            if agg.transient:
                # Reference Merger with transientState: emit
                # combine(input, summary) then reset summary to the initial
                # value (M/SummaryAggregation.java:107-119). `init` must be
                # the combine identity. After a resume, global carries the
                # restored partial window and is folded into the first emit.
                out = merged
                global_summary = agg.init()
            else:
                global_summary = merged
                out = global_summary
            locals_ = locals0_fn()
            dirty = False
            windows_closed += 1
            stats["windows_closed"] = windows_closed
            bus.inc("engine.windows_closed")
            if tracer is not None:
                tracer.instant("window_close", window=windows_closed,
                               mode=mode)
            return transform_fn(out) if transform_fn else out

        def close_pane():
            # Pane boundary (windowed mode): capture this merge window's
            # summary from fresh locals, push it into the ring, decay
            # TTL slots, and answer the W-pane window by suffix
            # aggregation — O(1) amortized combine dispatches per close,
            # never a W-pane re-merge and never a replay.
            nonlocal locals_, windows_closed, dirty, persist_vof, \
                last_seen
            faults_mod.inject("collective")
            t_h = time.perf_counter() if telemetry else 0.0
            pane = merge_locals(locals_)
            # Rebind BEFORE the pane enters the ring: folds donate their
            # summary argument, so the captured pane buffer must never
            # be passed to a fold again.
            locals_ = locals0_fn()
            dirty = False
            if win_persist_update is not None:
                persist_vof = win_persist_update(persist_vof, pane)
            ring.push(pane)
            windows_closed += 1
            stats["windows_closed"] = windows_closed
            stats["ring_combines"] = ring.combines
            bus.inc("engine.windows_closed")
            bus.inc("windows.panes_closed")
            if last_seen is not None:
                touched = np.asarray(win_touched(pane))
                last_seen[touched] = windows_closed
                assigned = int(agg.session.assigned)
                stale = np.zeros(last_seen.shape[0], dtype=bool)
                if assigned:
                    stale[:assigned] = (
                        windows_closed - last_seen[:assigned]
                    ) >= ttl_panes
                if stale.any():
                    # T >= W guarantees a stale slot is untouched in
                    # every live pane, so the hook can renumber the
                    # survivors to a dense prefix (reclaiming session
                    # capacity) and remap each pane without losing any
                    # window-visible state.
                    n_evict = int(stale.sum())
                    panes_np = [
                        jax.tree.map(np.asarray, p)
                        for p in ring.export_panes()
                    ]
                    panes2, persist2, surv = windowed_evict(
                        panes_np, np.asarray(persist_vof), stale
                    )
                    persist_vof = jnp.asarray(persist2)
                    ls2 = np.zeros_like(last_seen)
                    ls2[:len(surv)] = last_seen[surv]
                    last_seen = ls2
                    ring.reload(
                        [jax.tree.map(jnp.asarray, p) for p in panes2],
                        ring.panes_closed,
                    )
                    bus.inc("windows.evicted_slots", n_evict)
                bus.gauge("windows.live_slots", int(agg.session.assigned))
            q = ring.query()
            if win_query_fixup is not None:
                q = win_query_fixup(q, persist_vof)
            out = transform_fn(q) if transform_fn else q
            bus.gauge("windows.ring_live", ring.live)
            if telemetry:
                bus.observe("windows.pane_close_ms",
                            (time.perf_counter() - t_h) * 1e3)
            if tracer is not None:
                tracer.instant("pane_close", window=windows_closed,
                               ring_live=ring.live,
                               combines=ring.combines)
            with win_holder["lock"]:
                win_holder["val"] = {"window": windows_closed,
                                     "labels": out}
            return out

        close_fn = close_pane if windowed is not None else close_window

        def maybe_checkpoint(force=False):
            # Chunk-boundary-only checkpoints: every consumed edge is in
            # global_summary or locals_ — or, with allowed_lateness, in
            # the reorder buffer, which is serialized to a ``.lateness``
            # sidecar so resume re-seeds it (no drops). The sidecar is
            # written FIRST; resume verifies both files carry the same
            # position, so a crash between the two writes is detected
            # loudly instead of silently dropping buffered edges.
            nonlocal last_ckpt_windows, locals_, global_summary
            if not checkpoint_path:
                return
            if not force and windows_closed - last_ckpt_windows < checkpoint_every:
                return
            last_ckpt_windows = windows_closed
            t_ck = tracer.now() if tracer is not None else 0.0
            # Cadenced path flatten (SummaryAggregation.flatten): bound
            # the transform chase depth the pair-sized folds and delta
            # merges let grow, exactly at the cadence the full-capacity
            # cost is already being paid (the snapshot's device_get).
            # The flattened summary REPLACES the live state — labels
            # are identical by the flatten contract.
            if flatten_fn is not None and windowed is None:
                if accum:
                    locals_ = flatten_fn(locals_)
                else:
                    global_summary = flatten_fn(global_summary)
            if windowed is not None:
                # Ring snapshot: live panes stacked onto the STATIC
                # [W, ...] template (padded with init panes), plus the
                # persistent id map and TTL stamps — one recorded
                # position covers the ring AND the pane index, and
                # windowed checkpoints only ever fire at pane
                # boundaries (the cadence check above trips right after
                # a close, before any chunk folds into the next pane).
                panes = ring.export_panes()
                pads = [agg.init() for _ in range(windowed - len(panes))]
                snap = {
                    "panes": jax.tree.map(
                        lambda *ls: jnp.stack(ls), *(panes + pads)
                    )
                }
                if persist_vof is not None:
                    snap["persist"] = persist_vof
                if last_seen is not None:
                    snap["last_seen"] = jnp.asarray(last_seen)
            elif accum:
                snap = locals_  # the running summary holds every edge
            else:
                snap = (
                    merger_step(merge_locals(locals_), global_summary)
                    if dirty
                    else global_summary
                )
            from .checkpoint import save_checkpoint

            if allowed_lateness and "export" in lat_handle:
                st = lat_handle["export"]()
                save_checkpoint(
                    f"{checkpoint_path}.lateness.{chunks_consumed}",
                    st["chunks"],
                    position=chunks_consumed,
                    meta={
                        "wins": [int(w) for w in st["wins"]],
                        "closed_upto": st["closed_upto"],
                        "max_ts": st["max_ts"],
                    },
                )
            t_wall = time.perf_counter()
            ck_meta = {
                "name": agg.name,
                "windows": windows_closed,
                "current_window": current_window,
            }
            if windowed is not None:
                ck_meta["ring_live"] = ring.live
                ck_meta["windowed"] = windowed
            save_checkpoint(
                checkpoint_path, snap, position=chunks_consumed,
                meta=ck_meta,
            )
            ck_bytes = obs_bus.publish_checkpoint(bus, "engine",
                                                  checkpoint_path,
                                                  t0=t_wall)
            if wm is not None:
                # The durability point: every position the checkpoint
                # covers retires from the e2e ledger (ingress→durable
                # histogram) and the low watermark advances.
                wm.retire_durable("stream", chunks_consumed, bus=bus,
                                  prefix="engine")
                bus.gauge("engine.backlog_age_s",
                          round(wm.backlog_age("stream"), 6))
            if tracer is not None:
                cctx = tracer.ctx(("fold", chunks_consumed))
                clink = ({"trace": cctx[0], "parent": cctx[1]}
                         if cctx is not None else {})
                tracer.span("checkpoint", "checkpoint", t_ck,
                            position=chunks_consumed,
                            windows=windows_closed, bytes=ck_bytes,
                            **clink)
            if allowed_lateness:
                # Only after the main write is durable: stale sidecars
                # (older positions, or the legacy unstamped name) are no
                # longer the matching pair for ANY reachable resume.
                import glob as _glob
                import os as _os

                keep = f"{checkpoint_path}.lateness.{chunks_consumed}"
                for old in _glob.glob(
                    _glob.escape(checkpoint_path) + ".lateness*"
                ):
                    if old != keep:
                        try:
                            _os.unlink(old)
                        except OSError:
                            pass

        from ..utils.prefetch import prefetch

        def counted_chunks():
            # Window-mode ingest: chunks stay host-side through the
            # prefetch queue — the tumbling iterator reads ts/valid per
            # chunk on the host, and jit prunes dead arguments at
            # dispatch so only the fields the fold actually reads are
            # transferred. (The merge_every path's precombine and
            # device_fields H2D staging live in stage_unit/h2d_unit;
            # this iterator feeds window mode only.)
            nonlocal chunks_consumed
            for chunk in prefetch(iter(stream), prefetch_depth):
                # In window mode checkpoints fire only here, at chunk
                # boundaries: every edge of the chunks counted so far is in
                # locals_ or global_summary, so the recorded position is
                # consistent. (Mid-chunk "close" events are not safe points:
                # the chunk's later-window edges are not folded yet.)
                if window_ms is not None and chunks_consumed > skip_until:
                    maybe_checkpoint()
                chunks_consumed += 1
                stats["chunks"] = chunks_consumed
                if chunks_consumed <= skip_until:
                    continue
                if wm is not None:
                    wm.stamp("stream", chunks_consumed - 1)
                yield chunk

        # Exact 0-based stream position of each produced unit's first
        # chunk (written before the unit is yielded, read by stage_unit
        # possibly on a worker thread — strictly happens-after). Needed
        # because pre-grouped stacked units make unit sizes VARIABLE,
        # so ``skip_until + seq * batch`` no longer reconstructs the
        # position; the provider path keeps its wm_alloc counter.
        unit_base: dict = {}

        def produced_units():
            # Batched producer for merge_every mode: groups of up to
            # ``batch`` host chunks, numbered in stream order (the seq
            # feeds ordered stackers). Resume-skipped chunks are dropped
            # here (they were consumed in the checkpointed run;
            # chunks_consumed starts at skip_until). A LIST stream item
            # is a pre-grouped staged unit — a STACKED wire frame
            # (``IngestServer.compressed_payload_units`` /
            # ``chunk_units``) — and is yielded as its own unit: one
            # fold dispatch per frame, never re-split or merged with
            # neighbouring chunks.
            idx = 0
            seq = 0
            group: list = []
            group_lo = 0
            it = iter(stream)
            t_unit = tracer.now() if tracer is not None else 0.0
            while True:
                with timer("ingest_chunks"):
                    chunk = next(it, None)
                if chunk is None:
                    break
                if isinstance(chunk, list):
                    # Pre-grouped unit. Flush the accumulated per-chunk
                    # group first (stream order is the fold order).
                    if group:
                        unit_base[seq] = group_lo
                        if tracer is not None:
                            tracer.span("produce", "produce", t_unit,
                                        unit=seq, chunks=len(group))
                        yield seq, group
                        seq += 1
                        group = []
                        if tracer is not None:
                            t_unit = tracer.now()
                    lo = idx
                    idx += len(chunk)
                    if idx <= skip_until:
                        continue  # whole unit folded pre-checkpoint
                    if lo < skip_until:
                        # Mid-frame resume: the checkpoint position
                        # landed INSIDE this frame. The wire re-delivers
                        # the covering frame; only the unseen suffix
                        # folds — the exactly-once contract at chunk
                        # granularity over frame-granularity redelivery.
                        chunk = chunk[skip_until - lo:]
                        lo = skip_until
                    if len(chunk) > batch:
                        raise ValueError(
                            f"stacked unit of {len(chunk)} chunks "
                            f"exceeds fold_batch {batch} — size the "
                            "consumer's fold_batch to at least the wire "
                            "stack size (client stack=K)"
                        )
                    unit_base[seq] = lo
                    if tracer is not None:
                        tracer.span("produce", "produce", t_unit,
                                    unit=seq, chunks=len(chunk))
                    yield seq, chunk
                    seq += 1
                    if tracer is not None:
                        t_unit = tracer.now()
                    continue
                idx += 1
                if idx <= skip_until:
                    continue
                if not group:
                    group_lo = idx - 1
                group.append(chunk)
                if len(group) == batch:
                    unit_base[seq] = group_lo
                    if tracer is not None:
                        tracer.span("produce", "produce", t_unit,
                                    unit=seq, chunks=batch)
                    yield seq, group
                    seq += 1
                    group = []
                    if tracer is not None:
                        t_unit = tracer.now()
            if group:
                unit_base[seq] = group_lo
                if tracer is not None:
                    tracer.span("produce", "produce", t_unit,
                                unit=seq, chunks=len(group))
                yield seq, group

        def _pad_group(group):
            # Pad the final partial batch to the static batch size so the
            # stacked shapes (and hence the compiled program) never change.
            if len(group) == batch:
                return group
            c0 = group[0].to_numpy()
            zero = EdgeChunk(*(np.zeros_like(f) for f in c0))
            return group + [zero] * (batch - len(group))

        identity_payload = None
        if use_codec:
            from ..core.chunk import make_chunk

            empty = make_chunk(
                np.zeros(0, np.int64), np.zeros(0, np.int64),
                capacity=1, device=False,
            )
            identity_payload = agg.host_compress(empty)
        # Precompressed streams skip host_compress entirely, so the
        # per-unit staging work is attributed to a ``stack`` span/timer
        # stage — a traced run proves structurally that the consumer
        # paid ZERO compress time for bytes the producer shipped
        # compressed.
        stage_span = "stack" if precompressed else "compress"
        stage_timer_name = (
            "ingest_stack" if precompressed else "ingest_compress"
        )

        def stage_unit(unit):
            # Pipeline stage 1 — HOST compress only (the K-worker pool):
            # builds the unit's host payload; the H2D transfer is stage 2
            # (h2d_unit, a dedicated thread), so compress of unit i+2,
            # transfer of unit i+1 and the fold of unit i all overlap.
            # The unit's trace context is its seq: the compress span here,
            # the H2D span (buffer slot) and the fold span all carry it,
            # so a stalled chunk is attributable end to end.
            seq, group = unit
            # Pop unconditionally — with telemetry off nothing else
            # would, and the map must not grow with the stream.
            unit_base_seq = unit_base.pop(seq, None)
            if wm is not None:
                # Ingress stamp at reader parse/staging time (both the
                # single-iterator and sharded-provider paths stage
                # through here). First-stamp-wins: a wire-receive stamp
                # for the same position is never overwritten. On the
                # single-iterator path unit seq × batch maps exactly
                # onto the exactly-once chunk positions the
                # fold/checkpoint will retire; provider seqs are
                # lane-interleaved, so their positions come from the
                # dense wm_alloc counter instead (see its definition).
                if wm_alloc is not None:
                    for _ in range(len(group)):
                        wm.stamp("stream", wm_alloc())
                else:
                    # Exact recorded base (variable-size stacked units
                    # broke the uniform seq × batch arithmetic).
                    base = unit_base_seq
                    if base is None:
                        base = skip_until + seq * batch
                    for j in range(len(group)):
                        wm.stamp("stream", base + j)
            try:
                faults_mod.inject("codec")
                t0 = tracer.now() if tracer is not None else 0.0
                payload, k = _stage_unit_inner(seq, group)
                edges = None
                if tracer is not None:
                    # Payload items carry no valid mask: edge attribution
                    # is a chunk-path extra the compressed wire forgoes.
                    edges = (
                        None if precompressed else _group_edges(group)
                    )
                    tracer.span(
                        stage_span,
                        f"{stage_span}/"
                        f"{threading.current_thread().name}",
                        t0, unit=seq, chunks=k, edges=edges,
                        payload_bytes=_payload_nbytes(payload),
                        queue_depth=bus.gauges.get(
                            "pipeline.staged_depth", 0),
                    )
                return payload, k, seq, edges
            except BaseException:
                # Release the unit's assignment turn so units parked
                # behind it in await_turn unwind instead of hanging the
                # pool at interpreter exit (the error itself still
                # propagates to the consumer via prefetch_map).
                if agg.stack_ordered and agg.on_stage_error is not None:
                    agg.on_stage_error(seq)
                raise

        def _stage_unit_inner(seq, group):
            k = len(group)
            if use_codec:
                with timer(stage_timer_name):
                    if precompressed:
                        # Producer-compressed payloads ride as-is: the
                        # stack/pad/mesh-split below is the ONLY staging
                        # work left on this side — plus the plan's id
                        # range check (payload_to_chunk parity: an
                        # out-of-range id must raise HERE, not silently
                        # drop/clamp in the device scatter).
                        payloads = [
                            jax.tree.map(np.asarray, p) for p in group
                        ]
                        if agg.codec_payload_check is not None:
                            for p in payloads:
                                agg.codec_payload_check(p)
                    else:
                        payloads = [agg.host_compress(c) for c in group]
                    if k < batch:
                        payloads += [identity_payload] * (batch - k)
                    if agg.stack_payloads is not None:
                        if agg.stack_ordered:
                            stacked = agg.stack_payloads(
                                payloads, max(S, 1), seq=seq
                            )
                        else:
                            stacked = agg.stack_payloads(
                                payloads, max(S, 1)
                            )
                    else:
                        stacked = jax.tree.map(
                            lambda *ls: np.stack(ls), *payloads
                        )
                    if S > 1:
                        # [K', ...] -> [S, K'/S, ...]: chunk-data-parallel
                        # split of the batch axis across devices (a
                        # combining stacker may have reduced K to K' =
                        # any multiple of S).
                        stacked = jax.tree.map(
                            lambda x: x.reshape(
                                (S, x.shape[0] // S) + x.shape[1:]
                            ),
                            stacked,
                        )
                return stacked, k
            with timer("ingest_compress"):
                if batch > 1:
                    group = [
                        host_precombine(c) if host_precombine else c
                        for c in group
                    ]
                    group = [c.to_numpy() for c in _pad_group(group)]
                    stacked = EdgeChunk(
                        *(np.stack(fs) for fs in zip(*group))
                    )
                    return stacked, k
                c = group[0]
                if host_precombine is not None:
                    c = host_precombine(c)
                return c, k

        def h2d_unit(staged):
            # Pipeline stage 2 — the double-buffered H2D leg: device_put
            # of unit i+1 is issued (and, with h2d_depth > 0, completed on
            # its own thread) while the fold of unit i is in flight. The
            # block lands HERE, not on the consumer, so the recorded h2d
            # time is the real transfer and the fold dispatch never waits
            # on an in-flight upload.
            payload, k, seq, edges = staged
            faults_mod.inject("h2d")
            t0 = tracer.now() if tracer is not None else 0.0
            with timer("h2d"):
                if use_codec:
                    if S > 1:
                        dev = mesh_lib.device_put_sharded_leading(m, payload)
                    else:
                        dev = jax.device_put(payload)
                    jax.block_until_ready(dev)
                elif device_fields:
                    dev = payload._replace(**{
                        f: jax.device_put(getattr(payload, f))
                        for f in device_fields
                    })
                    jax.block_until_ready(
                        [getattr(dev, f) for f in device_fields]
                    )
                else:
                    dev = payload
            if tracer is not None:
                # Slot attribution: which double buffer this unit landed
                # in (seq mod depth — the rotation the prefetch leg runs).
                slot = seq % h2d_depth if h2d_depth > 0 else 0
                tracer.span(
                    "h2d", f"h2d/slot{slot}", t0, unit=seq, chunks=k,
                    slot=slot,
                    queue_depth=bus.gauges.get("pipeline.h2d_depth", 0),
                )
            return dev, k, seq, edges

        if window_ms is not None:
            # Tumbling timestamp windows via the shared iterator
            # (core/windows.py): no-data windows never fire, late edges are
            # dropped+counted (ascending-ts contract, allowedLateness=0).
            from ..core.windows import tumbling_window_events

            try:
                win_seq = 0
                wm_unit = 0  # span unit id (window mode is consumer-serial)
                for kind, w, chunk, _n in tumbling_window_events(
                    counted_chunks(), window_ms, stats,
                    initial_window=current_window,
                    allowed_lateness=allowed_lateness,
                    state_handle=lat_handle, initial_state=lat_state,
                ):
                    if kind == "close":
                        t_merge = tracer.now() if tracer is not None else 0.0
                        t_h = time.perf_counter() if telemetry else 0.0
                        out = close_window()
                        if telemetry:
                            bus.observe("engine.merge_emit_ms",
                                        (time.perf_counter() - t_h) * 1e3)
                            wm.retire_fold("stream", chunks_consumed,
                                           bus=bus, prefix="engine")
                        if tracer is not None:
                            tracer.span("merge_emit", "merge_emit", t_merge,
                                        window=windows_closed)
                        publish_watermarks()
                        yield out
                    elif use_codec:
                        # The chunk is masked to window ``w``: compress it and
                        # fold the payload — the windowed wire rides the codec
                        # (the consumer loop is single-threaded, so stream
                        # order is the call order). On a mesh the chunk splits
                        # into S host slices, one payload row per device —
                        # the same batch-axis split as merge_every staging.
                        current_window = w
                        t0 = tracer.now() if tracer is not None else 0.0
                        with timer("ingest_compress"):
                            if S > 1:
                                parts = split_chunk_host(chunk, S)
                            else:
                                parts = [chunk]
                            payloads = [agg.host_compress(c) for c in parts]
                            if agg.stack_payloads is not None:
                                if agg.stack_ordered:
                                    stacked = agg.stack_payloads(
                                        payloads, S, seq=win_seq
                                    )
                                    win_seq += 1
                                else:
                                    stacked = agg.stack_payloads(payloads, S)
                            else:
                                stacked = jax.tree.map(
                                    lambda *ls: np.stack(
                                        [np.asarray(x) for x in ls]
                                    ),
                                    *payloads,
                                )
                            if S > 1:
                                stacked = jax.tree.map(
                                    lambda x: x.reshape(
                                        (S, x.shape[0] // S) + x.shape[1:]
                                    ),
                                    stacked,
                                )
                        if tracer is not None:
                            tracer.span("compress", "compress/window", t0,
                                        unit=wm_unit, window=int(w),
                                        payload_bytes=_payload_nbytes(stacked))
                            t0 = tracer.now()
                        with timer("h2d"):
                            if S > 1:
                                dev = mesh_lib.device_put_sharded_leading(
                                    m, stacked
                                )
                            else:
                                dev = jax.device_put(stacked)
                        if tracer is not None:
                            tracer.span("h2d", "h2d/slot0", t0, unit=wm_unit,
                                        slot=0)
                            t0 = tracer.now()
                        t_h = time.perf_counter() if telemetry else 0.0
                        with timer("fold_dispatch"):
                            locals_ = fold_codec(locals_, dev)
                        if telemetry:
                            bus.observe("engine.fold_dispatch_ms",
                                        (time.perf_counter() - t_h) * 1e3)
                        if tracer is not None:
                            tracer.span("fold", "fold", t0, unit=wm_unit,
                                        window=int(w))
                        wm_unit += 1
                        dirty = True
                    else:
                        current_window = w
                        t0 = tracer.now() if tracer is not None else 0.0
                        t_h = time.perf_counter() if telemetry else 0.0
                        locals_ = fold_step(locals_, chunk)
                        if telemetry:
                            bus.observe("engine.fold_dispatch_ms",
                                        (time.perf_counter() - t_h) * 1e3)
                        if tracer is not None:
                            tracer.span("fold", "fold", t0, unit=wm_unit,
                                        window=int(w))
                        wm_unit += 1
                        dirty = True
                # The iterator closes the final partial window itself; just make
                # sure the last state is durably checkpointed.
                if checkpoint_path and stats["windows_closed"]:
                    maybe_checkpoint(force=True)
            finally:
                # Stage accounting lands on the registry on ANY
                # exit — normal end, error, or the consumer
                # abandoning the emission stream mid-window (same
                # contract as the pipeline branch's teardown).
                timer.publish(bus)
        else:
            chunks_consumed = skip_until
            if use_codec:
                fold_unit = fold_codec
            elif batch > 1:
                fold_unit = fold_many
            else:
                fold_unit = fold_step
            from ..utils.prefetch import prefetch_map

            # The pipelined executor: compress on K workers, H2D on its
            # own thread (h2d_depth in-flight device buffers), folds
            # dispatched asynchronously by this consumer. The only
            # consumer-side synchronization is the merge_emit block at
            # each window close — steady-state folds neither block nor
            # allocate (state is donated).
            pipe_cancel = threading.Event()
            # Queue-depth gauges ride the prefetch enqueue hook only when
            # tracing (the bus write per unit is cheap, but the disabled
            # path stays contractually untouched).
            staged_gauge = h2d_gauge = None
            if tracer is not None:
                staged_gauge = lambda d: bus.gauge(  # noqa: E731
                    "pipeline.staged_depth", d)
                h2d_gauge = lambda d: bus.gauge(  # noqa: E731
                    "pipeline.h2d_depth", d)
            if source_provider is not None:
                # Sharded reader lanes: parse + compress run per-lane on
                # the provider's threads; the engine's stage closure is
                # handed over so codec/batch/precombine semantics (and
                # the compress spans, now on gelly-reader_<s> tracks)
                # stay identical to the single-iterator path.
                staged = source_provider.stage_units(
                    stage_unit, batch=batch, start=skip_until,
                    depth=prefetch_depth, cancel=pipe_cancel,
                    gauge=staged_gauge,
                )
            else:
                staged = prefetch_map(
                    stage_unit, produced_units(), depth=prefetch_depth,
                    workers=ingest_workers, cancel=pipe_cancel,
                    gauge=staged_gauge,
                )
            transferred = map(h2d_unit, staged)
            if h2d_depth > 0:
                transferred = prefetch(transferred, depth=h2d_depth,
                                       gauge=h2d_gauge)
            try:
                for unit, k, seq, edges in transferred:
                    # Last-retired-chunk rule: a chunk counts toward the
                    # checkpoint position exactly when its fold is
                    # dispatched here; units still in the compress/H2D
                    # buffers are re-read on resume.
                    chunks_consumed += k
                    stats["chunks"] = chunks_consumed
                    t_fold = tracer.now() if tracer is not None else 0.0
                    t_h = time.perf_counter() if telemetry else 0.0
                    with timer("fold_dispatch"):
                        locals_ = fold_unit(locals_, unit)
                    bus.inc("engine.units_folded")
                    bus.inc("engine.chunks_folded", k)
                    if telemetry:
                        bus.observe("engine.fold_dispatch_ms",
                                    (time.perf_counter() - t_h) * 1e3)
                        staged_hw = max(staged_hw, bus.gauges.get(
                            "pipeline.staged_depth", 0))
                        wm.retire_fold("stream", chunks_consumed,
                                       bus=bus, prefix="engine")
                    if tracer is not None:
                        # Causal link to the wire: the server's staging
                        # bound each chunk position to its frame's
                        # trace context (ingest/server.py); the unit's
                        # first position carries it onto the fold span,
                        # and the fold frontier is re-bound under a
                        # distinct key so the covering checkpoint/merge
                        # can pick the chain up without clobbering
                        # staging bindings for incoming positions.
                        fctx = tracer.ctx(chunks_consumed - k)
                        fold_sid = tracer.next_span_id()
                        link = ({"trace": fctx[0], "parent": fctx[1]}
                                if fctx is not None else {})
                        tracer.span("fold", "fold", t_fold, unit=seq,
                                    chunks=k, edges=edges, span=fold_sid,
                                    **link, **fold_attrs)
                        tracer.bind_ctx(
                            ("fold", chunks_consumed),
                            fctx[0] if fctx is not None else tracer.trace_id,
                            fold_sid)
                        if edges:
                            meter.record(edges)
                            bus.inc("engine.edges_folded", edges)
                            meter.publish(bus, prefix="engine.throughput")
                        if hb is not None and hb.due():
                            # due() guards the field building: per-unit
                            # heartbeat cost is one clock compare.
                            hb.tick(
                                position=chunks_consumed,
                                eps=meter.snapshot()["edges_per_sec"],
                                windows=windows_closed,
                                staged_depth=bus.gauges.get(
                                    "pipeline.staged_depth", 0),
                                h2d_depth=bus.gauges.get(
                                    "pipeline.h2d_depth", 0),
                                # The serving-plane signals: staged
                                # high-water since the last beat, p99
                                # fold dispatch, worst backlog age.
                                staged_hw=staged_hw,
                                fold_p99_ms=round(bus.quantile(
                                    "engine.fold_dispatch_ms", 0.99), 3),
                                backlog_age_max_s=round(
                                    bus.watermarks.max_backlog_age(), 3),
                                slo_breaching=int(bus.gauges.get(
                                    "slo.breaching", 0)),
                            )
                            staged_hw = 0
                    chunks_in_window += k
                    dirty = True
                    if chunks_in_window >= merge_every:
                        t_merge = (tracer.now() if tracer is not None
                                   else 0.0)
                        t_h = (time.perf_counter() if telemetry
                               else 0.0)
                        with timer("merge_emit"):
                            out = close_fn()
                            # The window's ONE completion barrier: the
                            # emission (and with it every fold of the
                            # window) is ready before it is yielded.
                            jax.block_until_ready(out)
                        if telemetry:
                            bus.observe("engine.merge_emit_ms",
                                        (time.perf_counter() - t_h) * 1e3)
                        if tracer is not None:
                            mctx = tracer.ctx(("fold", chunks_consumed))
                            mlink = ({"trace": mctx[0], "parent": mctx[1]}
                                     if mctx is not None else {})
                            tracer.span("merge_emit", "merge_emit",
                                        t_merge, window=windows_closed,
                                        **mlink)
                        chunks_in_window = 0
                        publish_watermarks()
                        yield out
                    maybe_checkpoint()
                if dirty:
                    t_merge = tracer.now() if tracer is not None else 0.0
                    t_h = time.perf_counter() if telemetry else 0.0
                    with timer("merge_emit"):
                        out = close_fn()
                        jax.block_until_ready(out)
                    if telemetry:
                        bus.observe("engine.merge_emit_ms",
                                    (time.perf_counter() - t_h) * 1e3)
                    if tracer is not None:
                        mctx = tracer.ctx(("fold", chunks_consumed))
                        mlink = ({"trace": mctx[0], "parent": mctx[1]}
                                 if mctx is not None else {})
                        tracer.span("merge_emit", "merge_emit", t_merge,
                                    window=windows_closed, final=True,
                                    **mlink)
                    publish_watermarks()
                    yield out
                    maybe_checkpoint(force=True)
            finally:
                # Tear the pipeline down outermost-first on ANY exit —
                # normal end, error, or the caller abandoning the
                # emission generator mid-stream. ``pipe_cancel`` goes
                # FIRST: the H2D prefetch thread may be parked inside
                # ``staged.__next__`` on a stalled source, where a
                # generator close cannot reach it ("generator already
                # executing") — the event ends that parked get within
                # one poll, making the closes below deterministic rather
                # than best-effort, so abandoning the emission stream can
                # never leave compress workers consuming the source (and
                # advancing a stateful codec session) in the background.
                import time as _time

                pipe_cancel.set()
                close = getattr(transferred, "close", None)
                if close is not None:
                    close()
                deadline = _time.monotonic() + 2.0
                while True:
                    try:
                        staged.close()
                        break
                    except ValueError:
                        if _time.monotonic() >= deadline:
                            break  # daemon threads; cancel backstop
                        _time.sleep(0.01)
                if agg.ordered_wait_s is not None:
                    # Compress workers are torn down: move the turn-wait
                    # they accrued this run out of the compress stage —
                    # await_turn blocks INSIDE the ingest_compress timer
                    # context, and with K workers that wait would read as
                    # busy compress time in the overlap accounting.
                    timer.reattribute(
                        "ingest_compress", "codec_wait",
                        agg.ordered_wait_s() - wait0,
                    )
                # Stage accounting lands on the registry at teardown so
                # bench/tests read busy seconds off the bus without
                # holding the timer object.
                timer.publish(bus)

    if windowed is not None:
        out_stream = WindowedStream(gen, win_holder)
    else:
        out_stream = SummaryStream(gen)
    out_stream.stats = stats
    out_stream.timer = timer
    if fused:
        from .multiquery import MultiQueryStream

        out_stream = MultiQueryStream(out_stream, agg)
    return out_stream
