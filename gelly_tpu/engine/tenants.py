"""Multi-tenant serving engine: one dispatch advances N streams.

``run_aggregation`` dedicates the whole device to a single stream, yet
the r05 capture shows the fold dispatch is effectively free (0.0009s
against an 11.0s wall) — a service multiplexing thousands of
independent graph streams (one per tenant/session) onto one chip must
amortize dispatch, H2D and compile cost across tenants the way the
reference lets Flink multiplex many jobs onto shared slots
(PAPER.md §L1: ``GraphStream`` per job, slots shared by the cluster).

The engine here owns that multiplexing natively:

- **Tenant batching** (:class:`TenantBatch`): per-tenant summary states
  are stacked along a leading tenant axis and the compiled plan's
  fold/merge/transform are ``jax.vmap``-ed over it
  (:func:`~gelly_tpu.engine.aggregation._compiled_tenant_plan`), so ONE
  donated dispatch advances every lane of a tier. Lane widths grow by
  doubling — a stream of admissions compiles O(log N) programs.
- **Capacity tiers**: tenants are admitted into named tiers, each tier
  one ``SummaryAggregation`` plan (its ``vertex_capacity`` is the
  tier's capacity class) and one chunk capacity; all tenants of a tier
  share one compiled program per lane width, keyed like
  ``fold_backend``/``merge_mode`` in the engine's plan cache.
- **Fair-share windowing** (:class:`MultiTenantEngine`): per-tenant
  chunk queues; every scheduling round advances each backlogged tenant
  by at most one chunk, and a tenant with nothing pending contributes
  a no-op MASKED lane — stragglers never stall the batch, and every
  backlogged tenant advances at the same chunk rate. Starvation is
  observable: ``tenants.starved_windows`` counts live-tenant lanes
  that went masked in a dispatch.
- **Compressed tiers** (``add_tier(..., compressed=True)``): lanes
  fold PRE-COMPRESSED codec payloads — compressed once, at the
  producer (the submitter's thread via :meth:`MultiTenantEngine.
  submit_payload`, or a wire client before send) — through a vmapped
  ``fold_codec``, so the ~0.25 B/edge codec wire win covers tenant
  streams and the engine never re-pays host compress for bytes the
  producer shipped compressed. :meth:`TenantBatch.stack_payloads`
  stacks one payload per lane (variable-length wire keys pad to a
  shared power-of-two bucket from the plan's ``codec_pad_values``).
  Snapshots are bit-identical to the raw tier's; ``stack_ordered``
  codecs are refused (their id sessions need global stream order).
- **Per-tenant exactly-once checkpoints**: each tenant's lane is
  snapshotted through its own :class:`~gelly_tpu.engine.resilience.
  CheckpointManager` rotation (distinct filename prefixes in one
  shared directory), riding the existing position-header/CRC
  checkpoint format unchanged. The recorded position is the tenant's
  last DISPATCHED chunk at a window close — resume re-reads exactly
  the un-folded suffix, bit-identical to an unkilled run
  (``tests/_tenants_crash_child.py`` proves it under SIGKILL).
- **Live queries** (:meth:`MultiTenantEngine.query` /
  :meth:`~MultiTenantEngine.labels`): reads are answered from the last
  merge-window snapshot (the vmapped ``transform`` output, or a real
  device copy for transform-less plans), swapped in under a lock that
  is held only for the reference swap — a query never blocks a window
  close and a window close never blocks a query; staleness is bounded
  by ONE merge window.

The fold loop runs inline (:meth:`~MultiTenantEngine.drain`, finite
workloads) or on a scheduler thread (:meth:`~MultiTenantEngine.start`,
serving mode) — queries and submits are safe from any thread in both
modes. In serving mode an idle scheduler flushes partial windows
(emit-what-you-have), so slow tenants still see fresh snapshots.
"""

from __future__ import annotations

import logging
import threading
import time as _time
from collections import deque
from typing import Any, Iterator

import jax
import jax.numpy as jnp
import numpy as np

from ..core.chunk import EdgeChunk
from ..obs import bus as obs_bus
from ..obs import tracing as obs_tracing
from .aggregation import SummaryAggregation, _compiled_tenant_plan
from .qos import (
    QOS_LIMITED,
    QOS_OK,
    QOS_PARKED,
    QOS_SHED,
    AdmissionRefused,
    QosController,
)

logger = logging.getLogger("gelly_tpu.tenants")


# The serving-plane telemetry guard (histograms + e2e watermarks) —
# the ONE shared definition in obs.bus; callers bind the result and
# never touch the bus histograms otherwise.
_telemetry_on = obs_bus.telemetry_on


def tenant_prefix(tenant_id) -> str:
    """Injective, filesystem-safe checkpoint prefix for a tenant id.

    Every character outside ``[A-Za-z0-9_]`` percent-escapes (``%`` is
    itself escaped, so the map is injective), which keeps the prefix
    free of ``-`` — the rotation separator ``CheckpointManager`` splits
    file names on. Without this, ids "7" and "7-0" would glob into
    each other's rotations (one tenant pruning/loading another's
    checkpoints)."""
    s = str(tenant_id)
    return "t" + "".join(
        c if (c.isascii() and (c.isalnum() or c == "_"))
        else "%" + "".join(f"{b:02x}" for b in c.encode("utf-8"))
        for c in s
    )


def _normalize_payload(payload):
    """Host-normalize a pre-compressed tenant payload (a codec
    ``host_compress`` output: a dict of arrays or one ndarray) for
    lane stacking."""
    if isinstance(payload, EdgeChunk):
        raise ValueError(
            "got an EdgeChunk on a compressed tier — compressed tiers "
            "fold pre-compressed codec payloads (the plan's "
            "host_compress output); compress at the producer or use a "
            "raw tier (submit())"
        )
    if isinstance(payload, dict):
        out = {}
        for k, v in payload.items():
            arr = np.asarray(v)
            if arr.dtype == object:
                # np.asarray on e.g. a nested dict "succeeds" as a 0-d
                # object array — which would poison the tier template
                # and only blow up later on the scheduler thread; the
                # raise-to-the-submitter contract demands it fail HERE.
                raise ValueError(
                    f"payload key {k!r} is not an array (got "
                    f"{type(v).__name__}) — compressed tiers take one "
                    "FLAT dict of arrays (a codec host_compress "
                    "output); nested payloads (e.g. a fused multi-"
                    "query codec dict) have no lane-stacking support"
                )
            out[k] = arr
        return out
    arr = np.asarray(payload)
    if arr.dtype == object:
        raise ValueError(
            f"cannot normalize payload of type "
            f"{type(payload).__name__} — expected a dict of arrays or "
            "one ndarray (a codec host_compress output)"
        )
    return arr


def _normalize_chunk(chunk: EdgeChunk, capacity: int) -> EdgeChunk:
    """Host-normalize a tenant chunk for cross-tenant stacking: fixed
    dtypes for the id columns (folds read the dense ``src``/``dst``
    slots; ``raw_*`` widths vary by source and are widened to i64 so
    every tenant's chunks stack into one [N, C] batch)."""
    h = chunk if chunk.is_host() else chunk.to_numpy()
    if h.capacity != capacity:
        raise ValueError(
            f"tenant chunk capacity {h.capacity} != tier chunk capacity "
            f"{capacity} — all tenants of a tier share one static shape"
        )
    return h._replace(
        src=np.asarray(h.src, np.int32),
        dst=np.asarray(h.dst, np.int32),
        raw_src=np.asarray(h.raw_src, np.int64),
        raw_dst=np.asarray(h.raw_dst, np.int64),
        ts=np.asarray(h.ts, np.int64),
        event=np.asarray(h.event, np.int8),
        valid=np.asarray(h.valid, bool),
        val=np.asarray(h.val),
    )


class TenantBatch:
    """Stacked per-tenant summary state for one capacity tier.

    Owns the [lanes, ...]-stacked pytrees (window locals and, for
    non-accumulate plans, the carried global stack), the compiled
    :class:`~gelly_tpu.engine.aggregation.TenantPlan` for the current
    lane width, and the width-doubling growth path: widening
    re-initializes a wider stack and copies the existing lanes in, so
    admitted tenants keep their state across recompiles.
    """

    def __init__(self, agg: SummaryAggregation, chunk_capacity: int,
                 mesh=None, min_lanes: int = 1,
                 compressed: bool = False):
        self.agg = agg
        self.chunk_capacity = int(chunk_capacity)
        self.mesh = mesh
        self.min_lanes = max(1, int(min_lanes))
        # Compressed tier: lanes fold pre-compressed codec payloads
        # (``fold_codec`` dispatch) instead of raw chunks — the shared
        # compression plane's "compress once, at the producer" leg.
        self.compressed = bool(compressed)
        if compressed and (agg.fold_compressed is None
                           or agg.host_compress is None):
            # host_compress is required too: masked lanes fold the
            # codec's identity payload (host_compress of an empty
            # chunk), and a missing one would otherwise surface only
            # at the first dispatch with a drained lane — a config
            # error that must fail at registration.
            missing = ("fold_compressed" if agg.fold_compressed is None
                       else "host_compress")
            raise ValueError(
                f"aggregation '{agg.name}' has no {missing} — a "
                "compressed tier folds pre-compressed codec payloads "
                "(and pads masked lanes with the codec's identity "
                "payload); build the plan with its ingest codec on "
                "(e.g. ingest_combine=True) or register a raw tier"
            )
        if agg.requires_codec and not compressed:
            raise ValueError(
                f"aggregation '{agg.name}' folds ONLY through its "
                "ingest codec (requires_codec); register the tier with "
                "compressed=True so lanes fold payloads, not raw chunks"
            )
        self.lanes = 0
        self.plan = None
        # The accumulate plan (SummaryAggregation.fold_accumulates): one
        # running stacked summary, no per-window merger — the same
        # physical-plan specialization the single-stream engine applies
        # at S == 1.
        self.accum = agg.fold_accumulates and not agg.transient
        self.state = None  # accum: the running stack; else: window locals
        self.global_ = None  # non-accum only: the carried global stack
        self.sharding = None  # tenant-axis NamedSharding on an S>1 mesh
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel import mesh as mesh_lib
            from ..parallel.mesh import SHARD_AXIS

            if mesh_lib.num_shards(mesh) > 1:
                self.sharding = NamedSharding(mesh, P(SHARD_AXIS))
        self._zero_chunk: EdgeChunk | None = None
        self._template: EdgeChunk | None = None
        self._payload_template: dict | None = None
        self._identity_payload = None

    def _width_for(self, n: int) -> int:
        want = max(self.min_lanes, n, 1)
        w = 1 << max(0, want - 1).bit_length()
        if self.sharding is not None:
            from ..parallel import mesh as mesh_lib

            S = mesh_lib.num_shards(self.mesh)
            w = -(-max(w, S) // S) * S
        return w

    def ensure_lanes(self, n: int) -> None:
        """Grow the stack to hold ``n`` lanes (pow-2 widths; existing
        lanes copied into the widened stack)."""
        if self.plan is not None and n <= self.lanes:
            return
        width = self._width_for(n)
        plan = _compiled_tenant_plan(self.agg, width, mesh=self.mesh)
        old_lanes = self.lanes

        def widen(old):
            fresh = plan.init()
            if old is None or old_lanes == 0:
                return fresh
            # Eager per-widening copy (O(log N) times per run): the old
            # lanes land in the low rows of the fresh stack.
            return jax.tree.map(
                lambda f, o: f.at[:old_lanes].set(o), fresh, old
            )

        self.state = widen(self.state)
        if not self.accum:
            self.global_ = widen(self.global_)
        self.plan = plan
        self.lanes = width

    def shrink(self, keep_lanes: list, width: int) -> None:
        """Rebuild the stack at a SMALLER width (idle-lane reclamation):
        row ``keep_lanes[i]`` of the old stack lands in lane ``i``;
        every other lane is dropped — callers snapshot evicted lanes
        FIRST (the engine parks evicted tenants' final rows host-side
        so their queries keep answering). Shrinking back to a width
        the growth path already compiled is a plan-cache hit."""
        if width >= self.lanes or width < len(keep_lanes):
            raise ValueError(
                f"shrink width {width} must be < current {self.lanes} "
                f"and >= the {len(keep_lanes)} kept lanes"
            )
        plan = _compiled_tenant_plan(self.agg, width, mesh=self.mesh)
        idx = np.asarray(keep_lanes, np.int32)

        def compact(old):
            fresh = plan.init()
            if old is None or idx.size == 0:
                return fresh
            rows = jax.tree.map(lambda l: l[idx], old)
            return jax.tree.map(
                lambda f, r: f.at[: idx.size].set(r), fresh, rows
            )

        self.state = compact(self.state)
        if not self.accum:
            self.global_ = compact(self.global_)
        self.plan = plan
        self.lanes = width

    def set_lane(self, lane: int, host_state) -> None:
        """Overwrite one lane's RUNNING summary from a host pytree
        (checkpoint resume). For non-accumulate plans the restored
        summary is the tenant's carried global; its window locals stay
        fresh (new lanes initialize fresh in :meth:`ensure_lanes`)."""
        target = "state" if self.accum else "global_"
        cur = getattr(self, target)
        setattr(self, target, jax.tree.map(
            lambda l, h: l.at[lane].set(jnp.asarray(h)), cur, host_state,
        ))

    def slice_lane(self, lane: int):
        """Device slice of one tenant's RUNNING summary (accum: the live
        stack; non-accum: the carried global — call at a window close,
        when locals are freshly merged)."""
        src = self.state if self.accum else self.global_
        return jax.tree.map(lambda l: l[lane], src)

    def check_template(self, chunk: EdgeChunk) -> None:
        """Validate a normalized chunk against the tier template (the
        first chunk seen sets it). The engine calls this BEFORE a chunk
        is queued, so a mismatch (e.g. a divergent ``val`` dtype
        ``_normalize_chunk`` leaves alone) raises to the SUBMITTER —
        were it first detected at stack time, the error would kill the
        scheduler thread for every tenant, after the round had already
        popped other tenants' chunks."""
        if self._template is None:
            self._template = chunk
            self._zero_chunk = EdgeChunk(
                *(np.zeros_like(f) for f in chunk)
            )
            return
        for name, f, tf in zip(EdgeChunk._fields, chunk, self._template):
            if f.dtype != tf.dtype or f.shape != tf.shape:
                raise ValueError(
                    f"tenant chunk field {name!r} ({f.dtype}{f.shape})"
                    f" differs from the tier template "
                    f"({tf.dtype}{tf.shape}) — tenants of a tier must"
                    " ship identically-shaped chunks"
                )

    def stack_chunks(self, per_lane: list) -> tuple:
        """Host-stack one chunk (or a masked zero chunk) per lane into
        the [lanes, C] batch + the bool[lanes] active mask."""
        first = next((c for c in per_lane if c is not None), None)
        if first is None:
            raise ValueError("stack_chunks needs at least one live lane")
        for c in per_lane:
            if c is not None:
                self.check_template(c)
        rows = [c if c is not None else self._zero_chunk for c in per_lane]
        rows += [self._zero_chunk] * (self.lanes - len(per_lane))
        stacked = EdgeChunk(*(np.stack(fs) for fs in zip(*rows)))
        active = np.zeros((self.lanes,), bool)
        active[: len(per_lane)] = [c is not None for c in per_lane]
        return stacked, active

    # ------------------------------------------------- compressed tiers

    def _identity(self):
        # The masked-lane filler payload: the plan's own compression of
        # an empty chunk (what the engine pads short codec units with).
        if self._identity_payload is None:
            from ..core.chunk import make_chunk

            empty = make_chunk(
                np.zeros(0, np.int64), np.zeros(0, np.int64),
                capacity=1, device=False,
            )
            # host_compress presence is a compressed-tier construction
            # invariant (__init__ refuses plans without it), so this
            # call cannot land on None.
            self._identity_payload = _normalize_payload(
                self.agg.host_compress(empty)
            )
        return self._identity_payload

    def check_payload_template(self, payload) -> None:
        """Validate a normalized pre-compressed payload against the
        tier template (first payload seen sets it) — same
        raise-to-the-submitter timing as :meth:`check_template`. Keys
        named in the plan's ``codec_pad_values`` may vary in length
        (they pad to a shared bucket at stack time); everything else
        must match shape and dtype exactly."""
        pad = self.agg.codec_pad_values or {}

        bound = 2 * self.chunk_capacity  # two endpoints per edge

        def describe(p):
            if isinstance(p, dict):
                out = {}
                for k, v in p.items():
                    v = np.asarray(v)
                    if k in pad:
                        if v.ndim != 1:
                            raise ValueError(
                                f"payload key {k!r} is declared "
                                "variable-length (codec_pad_values) "
                                f"but has ndim {v.ndim}; lane padding "
                                "covers 1-D wire arrays only"
                            )
                        if v.shape[0] > bound:
                            # The raw tier's chunk-capacity bound,
                            # translated: one tenant's oversized
                            # payload would otherwise inflate EVERY
                            # lane's padded bucket (memory + compile
                            # cache + fold work) — cross-tenant
                            # interference the tier design forbids.
                            raise ValueError(
                                f"payload key {k!r} carries "
                                f"{v.shape[0]} rows > the tier bound "
                                f"of 2 x chunk_capacity = {bound} — "
                                "compress smaller chunks or register "
                                "a larger tier"
                            )
                        out[k] = (v.dtype, None)
                    else:
                        out[k] = (v.dtype, v.shape)
                return out
            v = np.asarray(p)
            return {None: (v.dtype, v.shape)}

        tpl = describe(payload)
        if self._payload_template is None:
            self._payload_template = tpl
            return
        ref = self._payload_template
        if tpl != ref:
            raise ValueError(
                f"tenant payload ({tpl}) differs from the tier "
                f"template ({ref}) — tenants of a compressed tier must "
                "ship payloads from the SAME codec (fixed-shape keys "
                "identical; variable keys are those in the plan's "
                "codec_pad_values)"
            )

    def stack_payloads(self, per_lane: list) -> tuple:
        """Host-stack one pre-compressed payload (or the identity
        payload for masked lanes) per lane into ``[lanes, 1, ...]``
        leaves — each lane a K=1 batch, so the very same
        ``fold_compressed`` the engine's stacked-unit path compiles
        folds it under vmap — plus the bool[lanes] active mask.
        Variable-length keys pad to a shared power-of-two bucket
        (bounded program ladder, like ``bucket_stack_payloads``)."""
        first = next((p for p in per_lane if p is not None), None)
        if first is None:
            raise ValueError("stack_payloads needs at least one live lane")
        rows = [p if p is not None else self._identity()
                for p in per_lane]
        rows += [self._identity()] * (self.lanes - len(per_lane))
        active = np.zeros((self.lanes,), bool)
        active[: len(per_lane)] = [p is not None for p in per_lane]
        pad = self.agg.codec_pad_values or {}
        if isinstance(first, dict):
            # The engine's shared variable-length stacker does the
            # bucket math (one padding implementation, one ladder);
            # the lane axis just inserts the K=1 batch dim after it.
            # Bucket floor tracks the tier's own payload bound so tiny
            # tiers never pad to the global 1024 default.
            from .aggregation import bucket_stack_payloads

            stacked = bucket_stack_payloads(
                rows, pad,
                min_bucket=min(1024, max(1, 2 * self.chunk_capacity)),
            )
            return {k: v[:, None] for k, v in stacked.items()}, active
        return np.stack([np.asarray(r) for r in rows])[:, None], active


class _Tenant:
    """Per-tenant bookkeeping. Fields shared between the scheduler
    thread and submitters/queriers are guarded by the engine lock."""

    __slots__ = ("tid", "tier", "lane", "queue", "source", "consumed",
                 "submitted", "finished", "done", "starved_windows",
                 "manager", "pending_state", "ready", "parked",
                 "parked_window", "park_pending", "parked_state",
                 "shed")

    def __init__(self, tid, tier: str, lane: int):
        self.tid = tid
        self.tier = tier
        self.lane = lane
        self.queue: deque = deque()
        self.source: Iterator | None = None
        self.consumed = 0  # chunks whose fold was dispatched
        # Monotonic dispatch-order position of the NEXT enqueued chunk
        # (resume base + chunks ever queued). The e2e watermark stamps
        # key off this, NOT ``consumed + len(queue)``: the scheduler
        # pops the queue and bumps ``consumed`` in two separate lock
        # windows, so the sum transiently under-counts by one and a
        # submit landing in that window would collide with (and lose)
        # the previous chunk's stamp.
        self.submitted = 0
        self.finished = False  # no more input will arrive
        self.done = False  # finished AND queue drained
        self.starved_windows = 0
        self.manager = None
        self.pending_state = None  # host pytree awaiting lane write
        # Idle-lane reclamation evicted this tenant's lane: `parked`
        # holds its final snapshot row host-side (queries answer from
        # it; `lane` becomes -1), `parked_window` the window it was
        # taken at.
        self.parked = None
        self.parked_window = 0
        # QoS degradation ladder bookkeeping: `park_pending` marks a
        # tenant the controller parked whose lane is freed at the next
        # safe point (a window boundary — mid-window parks on non-accum
        # plans would lose window-local folds); `parked_state` holds
        # the RAW running summary row host-side at the park, so an
        # un-park can restore the lane bit-identically; `shed` marks a
        # stream the controller closed (queue dropped, wire NACKed).
        self.park_pending = False
        self.parked_state = None
        self.shed = False
        # False until admit() has installed the lane state and resume
        # position: a running scheduler must neither pull nor dispatch
        # a half-admitted tenant (it would fold into a fresh lane the
        # pending resume state then clobbers, and admit's final
        # ``consumed = position`` write would erase its increments).
        self.ready = False


class _Tier:
    __slots__ = ("name", "batch", "chunks_in_window", "snapshot",
                 "snapshot_lanes", "snapshot_window", "windows_closed",
                 "last_ckpt_window", "hw_active", "low_windows")

    def __init__(self, name: str, batch: TenantBatch):
        self.name = name
        self.batch = batch
        self.chunks_in_window = 0
        self.snapshot = None  # last closed window's stacked emission
        self.snapshot_lanes = 0  # stacked width of `snapshot`
        self.snapshot_window = 0
        self.windows_closed = 0
        self.last_ckpt_window = 0
        # Idle-lane reclamation bookkeeping: per-window high-water of
        # LIVE (not-done) lane occupants, and how many consecutive
        # closed windows that high-water stayed below width/2.
        self.hw_active = 0
        self.low_windows = 0


class MultiTenantEngine:
    """Admission + fair-share scheduling over tenant-batched folds.

    ``merge_every`` — dispatch rounds per merge window (each round
    advances every backlogged tenant by one chunk). ``checkpoint_dir``
    + ``checkpoint_every`` (windows) enable per-tenant exactly-once
    checkpoints; ``resume=True`` reloads each tenant's newest valid
    checkpoint at admission and skips its already-folded prefix.
    ``mesh`` (optional, S > 1 devices) shards the TENANT axis across
    the mesh — lanes are data-parallel, so the vmapped program
    partitions with no cross-lane collectives.

    Locking: ``_lock`` guards the tenant/tier tables, queues and
    snapshot references (held only for dict/deque/reference work —
    never across a dispatch, transfer or file write, so queries and
    submits stay wait-free against device work); ``_dispatch_lock``
    serializes batch-state mutation between the scheduler thread and
    admissions (lane widening must not interleave with a fold).
    """

    def __init__(self, *, merge_every: int = 1,
                 checkpoint_dir: str | None = None,
                 checkpoint_every: int = 1, resume: bool = False,
                 mesh=None, poll_s: float = 0.005,
                 reclaim_after: int | None = None,
                 qos: QosController | None = None):
        if merge_every < 1:
            raise ValueError(f"merge_every must be >= 1, got {merge_every}")
        if checkpoint_every < 1:
            raise ValueError(
                f"checkpoint_every must be >= 1, got {checkpoint_every}"
            )
        if reclaim_after is not None and reclaim_after < 1:
            raise ValueError(
                f"reclaim_after must be >= 1 windows, got {reclaim_after}"
            )
        # Idle-lane reclamation (None = off): when a tier's high-water
        # LIVE lane count stays below width/2 for `reclaim_after`
        # consecutive closed windows, the stack halves — done tenants'
        # lanes are evicted (their state snapshotted + final-
        # checkpointed first; queries keep answering from the parked
        # row) and live tenants compact into the low lanes. Lane
        # widths previously only grew (O(log N) compiles); shrinking
        # back to a compiled width is a plan-cache hit.
        self.reclaim_after = reclaim_after
        # The QoS policy plane (None = legacy uniform fair share):
        # weighted-fair DRR in _round, the admission ceiling in admit()
        # and the limit→park→shed degradation ladder in _qos_evaluate.
        # With a controller installed the watermark ledgers run even
        # without telemetry recording (_wmk_on) — backlog age IS the
        # policy signal, not just a dashboard.
        if qos is not None and not isinstance(qos, QosController):
            raise TypeError(
                f"qos must be a QosController, got {type(qos).__name__}"
            )
        self.qos = qos
        self._qos_next_eval = 0.0
        # Admissions deferred by the ceiling (admission="queue"):
        # (tenant_id, tier, chunks) retried as pressure drains.
        self._qos_waiting: deque = deque()
        # Durability hooks: callables (tenant_id, position) fired AFTER
        # each per-tenant durability point commits (checkpoint
        # rotation, park/eviction final save, or — with no checkpoint
        # dir — the window close). The ingest router registers the
        # checkpoint-gated per-tenant wire ack here. Always fired
        # outside the engine locks.
        self.on_durable: list = []
        # QoS transition hooks: callables (tenant_id, action, info) for
        # "limit"/"clear"/"park"/"unpark"/"shed" — the router maps
        # park/unpark/shed onto wire PAUSE/RESUME/NACK.
        self.on_qos: list = []
        self.merge_every = merge_every
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = checkpoint_every
        self.resume = resume
        self.mesh = mesh
        self.poll_s = poll_s
        self._lock = threading.Lock()
        self._dispatch_lock = threading.Lock()
        self._tiers: dict[str, _Tier] = {}
        self._tenants: dict[Any, _Tenant] = {}
        self._stop = threading.Event()
        self._work = threading.Event()
        self._thread: threading.Thread | None = None
        # Set by ingest.TenantRouter: the engine then re-publishes the
        # shared ``pipeline.staged_depth`` gauge every scheduler loop —
        # the router alone publishes only on submit, so a paused client
        # (no submits) would leave the gauge stuck above low_water and
        # the server's RESUME poll spinning forever.
        self.publish_staged_gauge = False
        # Optional SLO plane (obs/slo.SloPlane): attached via
        # attach_slo_plane, ticked from the scheduler loop at the gauge
        # cadence — per-tenant burn gauges and breach events ride the
        # same rate limit as the backlog gauges they evaluate.
        self._slo_plane = None
        self.stats = {"dispatches": 0, "chunks": 0, "windows_closed": 0,
                      "starved_lanes": 0, "reclaims": 0,
                      "lanes_reclaimed": 0}

    # ------------------------------------------------------------ control

    def attach_slo_plane(self, plane) -> None:
        """Attach an :class:`~gelly_tpu.obs.slo.SloPlane`: the
        scheduler loop ticks it at the backlog-gauge cadence with the
        engine's live tenant set, so per-tenant burn-rate gauges and
        breach events stay current without a second evaluation thread
        (don't also :meth:`~gelly_tpu.obs.slo.SloPlane.start` it)."""
        with self._lock:
            self._slo_plane = plane

    def add_tier(self, name: str, agg: SummaryAggregation,
                 chunk_capacity: int, min_lanes: int = 1,
                 compressed: bool = False) -> None:
        """Register a capacity tier: one plan + one chunk shape, shared
        by every tenant admitted into it. Plan constraints are
        validated at first lane build (see ``_compiled_tenant_plan``).

        ``compressed=True`` registers a COMPRESSED tier: tenants ship
        pre-compressed codec payloads (the plan's ``host_compress``
        output — compressed once, at the producer: the submitter's
        thread, or a wire client before send) via :meth:`submit_payload`
        / payload pull sources, and every scheduling round dispatches
        the vmapped ``fold_codec`` instead of the raw fold. Requires a
        plan with a stateless codec (``fold_compressed`` present, no
        ``stack_ordered``); bit-identical snapshots to the raw tier."""
        with self._lock:
            if name in self._tiers:
                raise ValueError(f"tier {name!r} already registered")
            self._tiers[name] = _Tier(
                name,
                TenantBatch(agg, chunk_capacity, mesh=self.mesh,
                            min_lanes=min_lanes, compressed=compressed),
            )

    def _wmk_on(self) -> bool:
        """True when the per-tenant watermark ledgers must run: QoS
        consumes backlog ages as a POLICY signal, so a controller keeps
        the ledgers on even when telemetry recording is off (histogram
        publication stays telemetry-gated at each site)."""
        return self.qos is not None or _telemetry_on()

    def tenant_ids(self) -> list:
        """Admitted tenant ids (the router's seq-seed enumeration)."""
        with self._lock:
            return list(self._tenants)

    def qos_state(self, tenant_id) -> str:
        """The tenant's QoS ladder state (``"ok"`` without a
        controller)."""
        return self.qos.state(tenant_id) if self.qos is not None else QOS_OK

    def _active_backlog_age(self) -> float:
        """Worst backlog age across ACTIVE (lane-holding, not-done)
        tenants — the admission/un-park pressure signal. Parked
        tenants' ledgers age by construction while held and must not
        hold the admission door shut (or their own release)."""
        wmk = obs_bus.get_bus().watermarks
        with self._lock:
            tids = [t.tid for t in self._tenants.values()
                    if t.lane >= 0 and not t.done]
        return max((wmk.backlog_age(tid) for tid in tids), default=0.0)

    def admit(self, tenant_id, tier: str, chunks=None) -> int:
        """Admit a tenant into ``tier``; returns its lane index.

        ``chunks`` — optional chunk source (an iterable/iterator, an
        ``EdgeStream``, or anything ``engine/resilience`` can make
        seekable); the scheduler pulls from it lazily, one chunk per
        scheduling round. Without one, feed the tenant with
        :meth:`submit` + :meth:`finish`. With ``resume=True`` the
        tenant's newest valid checkpoint is loaded and a seekable
        source is fast-forwarded past the recorded position (push-mode
        callers must replay from :meth:`position` themselves — the
        ingest router's ``resume_seq`` contract).

        With a :class:`~gelly_tpu.engine.qos.QosController` configured
        with ``admission_ceiling_s``, admission is refused (raises
        :class:`~gelly_tpu.engine.qos.AdmissionRefused`) or queued
        (returns ``-1``; the tenant is admitted automatically once
        active backlog drains below the ceiling) while
        ``tenants.backlog_age_max_s`` over ACTIVE tenants exceeds the
        ceiling.
        """
        qos = self.qos
        if qos is not None and qos.admission_ceiling_s is not None:
            age = self._active_backlog_age()
            if age > qos.admission_ceiling_s:
                bus = obs_bus.get_bus()
                if qos.admission == "queue":
                    with self._lock:
                        if tenant_id in self._tenants or any(
                            w[0] == tenant_id for w in self._qos_waiting
                        ):
                            raise ValueError(
                                f"tenant {tenant_id!r} already admitted"
                                " or queued"
                            )
                        if tier not in self._tiers:
                            raise ValueError(
                                f"unknown tier {tier!r} (registered: "
                                f"{sorted(self._tiers)})"
                            )
                        self._qos_waiting.append((tenant_id, tier, chunks))
                    bus.emit(
                        "qos.admissions_queued",
                        tenant=str(tenant_id),
                        backlog_age_s=round(age, 6),
                    )
                    return -1
                bus.emit(
                    "qos.admissions_refused",
                    tenant=str(tenant_id),
                    backlog_age_s=round(age, 6),
                )
                raise AdmissionRefused(
                    tenant_id, backlog_age_s=age,
                    ceiling_s=qos.admission_ceiling_s,
                )
        with self._lock:
            if tenant_id in self._tenants:
                raise ValueError(f"tenant {tenant_id!r} already admitted")
            tr = self._tiers.get(tier)
            if tr is None:
                raise ValueError(
                    f"unknown tier {tier!r} (registered: "
                    f"{sorted(self._tiers)})"
                )
            # Next free lane: 1 + the highest OCCUPIED lane (evicted
            # tenants hold lane -1, so reclaimed widths are reused by
            # later admissions instead of growing the stack forever).
            lane = 1 + max(
                (t.lane for t in self._tenants.values()
                 if t.tier == tier), default=-1,
            )
            t = _Tenant(tenant_id, tier, lane)
            self._tenants[tenant_id] = t
        # Heavy work (checkpoint load, plan compile, lane widening)
        # OUTSIDE the table lock — queries and submits stay responsive
        # during admission; the dispatch lock keeps the widening from
        # interleaving with an in-flight fold.
        position = 0
        if self.checkpoint_dir is not None:
            from .resilience import CheckpointManager

            # No dispatch lock needed here: manager construction reaps
            # only THIS tenant's ``<prefix>-*.npz.tmp`` leftovers, and
            # no writer for a not-yet-admitted tenant's prefix can be
            # in flight (admit refuses duplicates).
            t.manager = CheckpointManager(
                self.checkpoint_dir, prefix=tenant_prefix(tenant_id),
                async_write=False,
            )
            if self.resume:
                found = t.manager.load_latest(
                    like=tr.batch.agg.init()
                )
                if found is not None:
                    state, position, _meta, path = found
                    t.pending_state = jax.tree.map(np.asarray, state)
                    logger.info(
                        "tenant %r resuming from %s at chunk %d",
                        tenant_id, path, position,
                    )
        source = None
        if chunks is not None:
            from .resilience import _make_seekable

            source = iter(_make_seekable(chunks)(position))
        elif position:
            logger.info(
                "tenant %r resumed at chunk %d in push mode — the "
                "submitter must replay from that position", tenant_id,
                position,
            )
        with self._dispatch_lock:
            tr.batch.ensure_lanes(lane + 1)
            if t.pending_state is not None:
                tr.batch.set_lane(lane, t.pending_state)
                t.pending_state = None
        # Publish atomically: position, source and readiness land in one
        # locked write — the scheduler never sees a dispatchable tenant
        # whose resume position could still be overwritten.
        with self._lock:
            t.consumed = position
            t.submitted = position
            t.source = source
            t.ready = True
        if self._wmk_on():
            # Seed the per-tenant e2e ledger at the exactly-once resume
            # point: a resumed tenant's backlog re-ages from the
            # re-submitted chunks' arrival, never the wall clock.
            obs_bus.get_bus().watermarks.seed(tenant_id, position)
        self._work.set()
        return lane

    def submit(self, tenant_id, chunk: EdgeChunk) -> None:
        """Push one chunk onto a tenant's queue (any thread). Raises
        ``ValueError`` to the caller when the chunk doesn't match the
        tier's template — a malformed chunk must never reach the
        scheduler's dispatch path, where it would take down every
        tenant's fold loop."""
        with self._lock:
            t = self._tenants[tenant_id]
            if t.finished:
                raise ValueError(
                    f"tenant {tenant_id!r} is finished; no more chunks"
                )
            batch = self._tiers[t.tier].batch
        if batch.compressed:
            raise ValueError(
                f"tier {t.tier!r} is a compressed tier: it folds "
                "pre-compressed codec payloads — compress at the "
                "producer (the plan's host_compress) and use "
                "submit_payload()"
            )
        h = _normalize_chunk(chunk, batch.chunk_capacity)
        with self._lock:
            batch.check_template(h)
            if self._wmk_on():
                # Ingress stamp at the submit boundary, keyed by the
                # chunk's dispatch-order position (queue is FIFO per
                # tenant): the per-tenant e2e watermark's time zero.
                obs_bus.get_bus().watermarks.stamp(
                    tenant_id, t.submitted)
            t.submitted += 1
            t.queue.append(h)
        self._work.set()

    def submit_payload(self, tenant_id, payload) -> None:
        """Push one PRE-COMPRESSED codec payload (the tier plan's
        ``host_compress`` output) onto a compressed-tier tenant's queue
        (any thread) — the producer-side half of the shared compression
        plane: the engine never re-compresses what the submitter (or a
        wire client) already reduced. Raises to the caller on a payload
        that doesn't match the tier's codec template."""
        with self._lock:
            t = self._tenants[tenant_id]
            if t.finished:
                raise ValueError(
                    f"tenant {tenant_id!r} is finished; no more chunks"
                )
            batch = self._tiers[t.tier].batch
        if not batch.compressed:
            raise ValueError(
                f"tier {t.tier!r} is a raw tier (add_tier("
                "compressed=False)); submit() chunks instead, or "
                "register the tier with compressed=True"
            )
        h = _normalize_payload(payload)
        if batch.agg.codec_payload_check is not None:
            # The plan's own id range check (payload_to_chunk parity):
            # an out-of-range id raises HERE, on the producer, instead
            # of silently dropping/clamping in the device scatter.
            batch.agg.codec_payload_check(h)
        with self._lock:
            batch.check_payload_template(h)
            if self._wmk_on():
                obs_bus.get_bus().watermarks.stamp(
                    tenant_id, t.submitted)
            t.submitted += 1
            t.queue.append(h)
        self._work.set()

    def finish(self, tenant_id) -> None:
        """Mark a push-mode tenant's stream complete: once its queue
        drains, the tenant is done."""
        with self._lock:
            self._tenants[tenant_id].finished = True
        self._work.set()

    def position(self, tenant_id) -> int:
        """Chunks folded for this tenant (the exactly-once resume point
        is the newest checkpoint at or below this)."""
        with self._lock:
            return self._tenants[tenant_id].consumed

    def chunk_capacity(self, tier: str) -> int:
        """The tier's static chunk capacity (wire routers size their
        payload→chunk padding from it)."""
        with self._lock:
            return self._tiers[tier].batch.chunk_capacity

    def queue_depth(self, tenant_id=None) -> int:
        with self._lock:
            if tenant_id is not None:
                return len(self._tenants[tenant_id].queue)
            return sum(len(t.queue) for t in self._tenants.values())

    def starved_windows(self, tenant_id) -> int:
        with self._lock:
            return self._tenants[tenant_id].starved_windows

    # ------------------------------------------------------------ queries

    def query(self, tenant_id, v: int | None = None):
        """Read a tenant's last merge-window snapshot (staleness bound:
        one merge window). ``v`` indexes array snapshots (labels /
        degrees); ``None`` returns the whole row. Returns ``None``
        before the first window close, and for a tenant admitted after
        it (its lane is not in the stored snapshot). Never blocks a
        window close — the lock is held only to read the snapshot
        reference."""
        with self._lock:
            t = self._tenants[tenant_id]
            tier = self._tiers[t.tier]
            snap = tier.snapshot
            lane = t.lane
            width = tier.snapshot_lanes
            # The parked row answers while the tenant holds no lane OR
            # while the published snapshot predates / doesn't cover the
            # lane it was just un-parked onto (freshness guard: the row
            # clears in _close_window once a covering snapshot lands).
            parked = t.parked if (
                t.parked is not None
                and (lane < 0 or lane >= width
                     or tier.snapshot_window <= t.parked_window)
            ) else None
        if parked is not None:
            # Evicted by idle-lane reclamation (or QoS-parked): the
            # snapshot row was parked host-side before the lane was
            # freed.
            if v is None:
                return jax.tree.map(np.asarray, parked)
            return jax.tree.map(lambda l: np.asarray(l)[v], parked)
        if snap is None or lane < 0 or lane >= width:
            # A tenant admitted after the snapshot was taken has no
            # lane in it — and JAX CLAMPS out-of-bounds indices, so
            # snap[lane] would silently return the highest stacked
            # lane (another tenant's data) instead of failing.
            return None
        # D2H outside the lock: a slow transfer must not serialize the
        # scheduler's snapshot swap (or other queries).
        if v is None:
            return jax.tree.map(lambda l: np.asarray(l[lane]), snap)
        return jax.tree.map(lambda l: np.asarray(l[lane, v]), snap)

    # Canonical reads: labels(tenant, v) for CC tiers, degree(tenant, v)
    # for degree tiers — both the same snapshot indexing.
    labels = query
    degree = query

    def telemetry(self) -> dict:
        """Per-tenant serving-plane snapshot — the dict the STATS
        endpoint ships (``TenantRouter.attach`` wires it into every
        attached server's ``stats_fields``): position, queue depth,
        backlog-age watermark, snapshot staleness and starvation per
        tenant. Read-only; never blocks the scheduler beyond the table
        lock."""
        bus = obs_bus.get_bus()
        wmk = bus.watermarks
        # ONE locked pass (snapshot_window's fields read inline) so a
        # row's position and staleness describe the same instant and a
        # many-tenant STATS request takes the scheduler's table lock
        # once, not once per tenant.
        with self._lock:
            rows = []
            for t in self._tenants.values():
                tier = self._tiers[t.tier]
                if t.lane < 0:
                    win = t.parked_window  # evicted: the parked row
                elif t.lane >= tier.snapshot_lanes:
                    win = 0  # admitted after the snapshot was taken
                else:
                    win = tier.snapshot_window
                rows.append((t.tid, t.tier, t.lane, t.consumed,
                             len(t.queue), t.done, t.starved_windows,
                             win))
        states = self.qos.states() if self.qos is not None else {}
        out = {}
        for tid, tier_name, lane, pos, depth, done, starved, win in rows:
            out[str(tid)] = {
                "tier": tier_name,
                "lane": lane,
                "position": pos,
                "queue_depth": depth,
                "done": done,
                "starved_windows": starved,
                "backlog_age_s": round(wmk.backlog_age(tid), 6),
                "snapshot_window": win,
                "qos_state": states.get(tid, QOS_OK),
            }
        return out

    def snapshot_window(self, tenant_id) -> int:
        """Window number the tenant's snapshot was taken at (0 = none
        yet) — the query-staleness handle."""
        with self._lock:
            t = self._tenants[tenant_id]
            tier = self._tiers[t.tier]
            if t.parked is not None and (
                t.lane < 0 or t.lane >= tier.snapshot_lanes
                or tier.snapshot_window <= t.parked_window
            ):
                return t.parked_window  # parked/evicted row answers
            if t.lane < 0:
                return t.parked_window  # evicted: the parked row's window
            if t.lane >= tier.snapshot_lanes:
                return 0  # tenant admitted after the snapshot was taken
            return tier.snapshot_window

    # ------------------------------------------------------------ driving

    def start(self) -> "MultiTenantEngine":
        """Run the scheduler on a background thread (serving mode)."""
        with self._lock:
            if self._thread is not None:
                raise RuntimeError("engine already started")
            self._stop.clear()
            th = threading.Thread(
                target=self._drive_loop, daemon=True,
                name="gelly-tenants",
            )
            self._thread = th
        th.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        self._work.set()
        with self._lock:
            th, self._thread = self._thread, None
        if th is not None:
            th.join(timeout)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def drain(self) -> dict:
        """Run the scheduler INLINE until every admitted tenant is done
        (finite workloads / tests); returns ``{tenant_id: final
        snapshot row}`` from the last closed window.

        Caveat: with a QoS controller, drain() converges only because
        parked tenants un-park once the remaining active backlog
        drains below their threshold; a tenant parked under a policy
        with no un-park threshold (``backlog_budget_s=None`` +
        ``unpark_below_s=None``) would hold its queue forever —
        overloaded serving workloads belong on :meth:`start`."""
        self._run(until_idle=True)
        with self._lock:
            tids = list(self._tenants)
        return {tid: self.query(tid) for tid in tids}

    def _drive_loop(self) -> None:
        try:
            self._run(until_idle=False)
        except BaseException:
            logger.exception("tenant scheduler died")
            raise

    # ---------------------------------------------------------- internals

    def _pull_sources(self) -> None:
        # Refill empty queues from pull-mode sources (scheduler thread
        # only — sources are single-consumer; queue appends race only
        # with submit(), which locks).
        with self._lock:
            pulls = [
                t for t in self._tenants.values()
                if t.ready and t.source is not None and not t.finished
                and not t.queue
            ]
        for t in pulls:
            batch = self._tiers[t.tier].batch
            try:
                chunk = next(t.source, None)
                if chunk is None:
                    h = None
                elif batch.compressed:
                    # Compressed tiers pull PAYLOAD sources: the
                    # producer side of the stream already compressed.
                    h = _normalize_payload(chunk)
                    if batch.agg.codec_payload_check is not None:
                        batch.agg.codec_payload_check(h)
                else:
                    h = _normalize_chunk(chunk, batch.chunk_capacity)
                with self._lock:
                    if h is None:
                        t.finished = True
                    else:
                        if batch.compressed:
                            batch.check_payload_template(h)
                        else:
                            batch.check_template(h)
                        if self._wmk_on():
                            obs_bus.get_bus().watermarks.stamp(
                                t.tid, t.submitted)
                        t.submitted += 1
                        t.queue.append(h)
            except Exception:
                # Quarantine: one tenant's bad source/chunk must not
                # kill the scheduler for every other tenant. The tenant
                # stops advancing (its folded prefix stays queryable);
                # everyone else keeps dispatching.
                logger.exception(
                    "tenant %r: chunk source failed; quarantining "
                    "(stream truncated at chunk %d)", t.tid, t.consumed,
                )
                with self._lock:
                    t.finished = True

    def _run(self, until_idle: bool) -> None:
        bus = obs_bus.get_bus()
        tracer = obs_tracing.active_tracer()
        hb = None
        if tracer is not None and tracer.heartbeat_every_s is not None:
            from ..obs.heartbeat import Heartbeat

            hb = Heartbeat(tracer.heartbeat_every_s)
        gauge_next = 0.0  # first round always publishes
        while not self._stop.is_set():
            self._pull_sources()
            advanced = self._round(bus, tracer)
            with self._lock:
                for t in self._tenants.values():
                    if t.finished and not t.queue and not t.done:
                        t.done = True
                live = [t for t in self._tenants.values() if not t.done]
                queued = sum(len(t.queue) for t in live)
            bus.gauge("tenants.active", len(live))
            bus.gauge("tenants.queue_depth", queued)
            if self.publish_staged_gauge:
                bus.gauge("pipeline.staged_depth", queued)
            if self.qos is not None:
                self._qos_evaluate(bus)
            backlog_max = 0.0
            hb_due = hb is not None and hb.due()
            if _telemetry_on():
                # Rate-limited: backlog_age is O(1) amortized since the
                # watermark min-deque, but N gauge writes per dispatch
                # round still churn the bus for no reader, so dispatching
                # rounds refresh at most every 0.5 s. Idle rounds and due
                # heartbeats publish unconditionally (the converged view,
                # and the beat's headline field, stay fresh).
                now = _time.monotonic()
                if not advanced or hb_due or now >= gauge_next:
                    gauge_next = now + 0.5
                    wmk = bus.watermarks
                    with self._lock:
                        tids = [(t.tid, t.lane >= 0 and not t.done)
                                for t in self._tenants.values()]
                    for tid, active in tids:
                        # Every tenant, done ones included: a drained
                        # ledger publishes 0, so dashboards never show a
                        # finished tenant's last in-flight age forever.
                        age = wmk.backlog_age(tid)
                        if active:
                            # The headline max is over ACTIVE tenants
                            # only: a parked tenant's ledger ages by
                            # construction while held and must not pin
                            # the admission/un-park pressure signal.
                            backlog_max = max(backlog_max, age)
                        bus.gauge(f"tenants.t{tid}.backlog_age_s",
                                  round(age, 6))
                    bus.gauge("tenants.backlog_age_max_s",
                              round(backlog_max, 6))
                    if self._slo_plane is not None:
                        self._slo_plane.set_tenants(
                            [tid for tid, _ in tids])
                        try:
                            self._slo_plane.tick()
                        except Exception:
                            # Evaluation must never take the scheduler
                            # down with it — a bad spec degrades to a
                            # logged error, not a stalled dispatch loop.
                            logger.exception("SLO plane tick failed")
            if hb_due:
                extras = {}
                if self.qos is not None:
                    counts = self.qos.counts()
                    extras = {
                        "qos_limited": counts[QOS_LIMITED],
                        "qos_parked": counts[QOS_PARKED],
                        "qos_shed": counts[QOS_SHED],
                    }
                hb.tick(
                    tenants_active=len(live),
                    tenants_queue_depth=queued,
                    windows=self.stats["windows_closed"],
                    chunks=self.stats["chunks"],
                    starved=self.stats["starved_lanes"],
                    backlog_age_max_s=round(backlog_max, 3),
                    round_p99_ms=round(
                        bus.quantile("tenants.round_ms", 0.99), 3),
                    slo_breaching=int(bus.gauges.get(
                        "slo.breaching", 0)),
                    **extras,
                )
            if advanced:
                continue
            # Nothing dispatched this round: flush partial windows so
            # finished tenants' tails (and idle serving snapshots) emit.
            self._flush_partial(bus, tracer)
            if until_idle:
                if not live:
                    self._ensure_snapshots()
                    self._final_checkpoints()
                    return
                if not queued:
                    # Remaining live tenants are unfinished push-mode
                    # feeds (exhausted pull sources flip `finished` in
                    # _pull_sources): drain() would spin forever.
                    raise RuntimeError(
                        "drain() would wait forever: push-mode tenants "
                        f"({[t.tid for t in live]}) have no pending "
                        "chunks and were never finish()ed — call "
                        "finish(tenant) or use start() for serving mode"
                    )
                continue
            self._work.clear()
            self._work.wait(self.poll_s)

    def _round(self, bus, tracer) -> bool:
        """One scheduling round: every tier with pending work gets ONE
        vmapped dispatch advancing each backlogged tenant by one chunk.
        Returns True when any tier dispatched."""
        any_dispatch = False
        with self._lock:
            tiers = list(self._tiers.values())
        for tier in tiers:
            with self._lock:
                members = [
                    t for t in self._tenants.values()
                    if t.tier == tier.name and t.ready and t.lane >= 0
                ]
                # Reclamation high-water: LIVE lane occupants this round
                # (window-scoped; _maybe_reclaim reads and resets it).
                tier.hw_active = max(
                    tier.hw_active,
                    sum(1 for t in members if not t.done),
                )
                # Index by LANE, not member order: a half-admitted
                # neighbor (ready=False) must leave its lane masked,
                # never shift another tenant's chunk into it.
                width = 1 + max((t.lane for t in members), default=-1)
                per_lane: list = [None] * width
                took: list = []
                starved_tenants: list = []
                backlogged: list = []
                for t in members:
                    if t.park_pending:
                        # Park decided but not yet executed (waiting for
                        # the window boundary): hold the lane masked.
                        continue
                    if t.queue:
                        backlogged.append(t)
                    elif not t.finished and not t.done:
                        starved_tenants.append(t)
                granted = None
                if self.qos is not None and backlogged:
                    # Deficit-round-robin over policy weights replaces
                    # one-chunk-per-round uniformity. The controller's
                    # lock is a leaf — safe inside the table lock. A
                    # backlogged-but-ungranted tenant is NOT starved:
                    # it has work and is being paced by policy.
                    granted = self.qos.plan_round(
                        [t.tid for t in backlogged])
                for t in backlogged:
                    if granted is None or t.tid in granted:
                        per_lane[t.lane] = t.queue.popleft()
                        took.append(t)
            if not took:
                # No dispatch, no starvation: a starved window is a
                # masked no-op lane IN a dispatch, so an idle serving
                # engine polling empty queues must not inflate the
                # counters (the increments land below, with the other
                # post-dispatch accounting).
                continue
            starved = len(starved_tenants)
            batch = tier.batch
            t0 = tracer.now() if tracer is not None else 0.0
            telemetry = _telemetry_on()
            t_h = _time.perf_counter() if telemetry else 0.0
            with self._dispatch_lock:
                batch.ensure_lanes(len(per_lane))
                if batch.compressed:
                    stacked, active = batch.stack_payloads(per_lane)
                    fold = batch.plan.fold_codec
                else:
                    stacked, active = batch.stack_chunks(per_lane)
                    fold = batch.plan.fold
                dev = jax.device_put(stacked, batch.sharding)
                act = jax.device_put(active, batch.sharding)
                # ONE donated dispatch advances every lane of the tier.
                batch.state = fold(batch.state, dev, act)
            if telemetry:
                # Per-round latency distribution (stack + H2D + batched
                # fold dispatch): the signal a fair-share scheduler
                # budgets rounds against.
                bus.observe("tenants.round_ms",
                            (_time.perf_counter() - t_h) * 1e3)
            with self._lock:
                for t in took:
                    t.consumed += 1
                for t in starved_tenants:
                    t.starved_windows += 1
                self.stats["dispatches"] += 1
                self.stats["chunks"] += len(took)
                if starved:
                    self.stats["starved_lanes"] += starved
            if self._wmk_on():
                # Ingress→fold for every chunk this round advanced
                # (per-tenant histograms; stamps stay until durable).
                # Histogram publication stays telemetry-gated; the
                # ledger advance itself also feeds the QoS signal.
                for t in took:
                    bus.watermarks.retire_fold(
                        t.tid, t.consumed,
                        bus=bus if telemetry else None,
                        prefix=(f"tenants.t{t.tid}"
                                if telemetry else None))
            if starved:
                bus.inc("tenants.starved_windows", starved)
            bus.inc("tenants.dispatches")
            if batch.compressed:
                bus.inc("tenants.compressed_dispatches")
            bus.inc("tenants.chunks_folded", len(took))
            if tracer is not None:
                tracer.span(
                    "fold", f"tenants/{tier.name}", t0,
                    tier=tier.name, lanes=batch.lanes,
                    advanced=len(took), starved=starved,
                )
            tier.chunks_in_window += 1
            any_dispatch = True
            if tier.chunks_in_window >= self.merge_every:
                self._close_window(tier, bus, tracer)
        return any_dispatch

    # -------------------------------------------------------- QoS ladder

    def _qos_evaluate(self, bus) -> None:
        """The rate-limited QoS pass (scheduler thread only): advance
        every tenant's ladder state, execute pending parks at safe
        points, retry queued admissions, publish the ``qos.*`` gauges
        and fire ``on_qos`` hooks — all hook/bus work OUTSIDE the
        engine locks."""
        qos = self.qos
        now = _time.monotonic()
        wmk = obs_bus.get_bus().watermarks
        with self._lock:
            if now < self._qos_next_eval:
                return
            self._qos_next_eval = now + qos.eval_every_s
            rows = [(t, len(t.queue)) for t in self._tenants.values()
                    if t.ready and not t.done]
            active = [t.tid for t, _ in rows if t.lane >= 0]
        ages = {t.tid: wmk.backlog_age(t.tid) for t, _ in rows}
        active_max = max((ages[tid] for tid in active), default=0.0)
        events: list = []
        for t, depth in rows:
            action = qos.evaluate(
                t.tid, backlog_age_s=ages[t.tid], queue_depth=depth,
                active_backlog_max_s=active_max,
            )
            if action is None:
                continue
            info = {"backlog_age_s": round(ages[t.tid], 6),
                    "queue_depth": depth}
            if action == "limit":
                bus.emit("qos.rate_limited", tenant=str(t.tid), **info)
            elif action == "clear":
                bus.emit("qos.limit_cleared", tenant=str(t.tid), **info)
            elif action == "park":
                with self._lock:
                    t.park_pending = True
                bus.emit("qos.parked", tenant=str(t.tid), **info)
            elif action == "unpark":
                self._unpark_tenant(t)
                bus.emit("qos.unparked", tenant=str(t.tid), **info)
            elif action == "shed":
                info["chunks_dropped"] = self._shed_tenant(t, bus)
                bus.emit("qos.shed", tenant=str(t.tid), **info)
            events.append((t.tid, action, info))
        # Parks decided above (or in earlier passes) execute only at a
        # window boundary — a mid-window park would drop the lane's
        # un-merged folds. Idle tiers (chunks_in_window == 0) are at a
        # boundary RIGHT NOW; busy tiers park in _close_window.
        with self._lock:
            idle_tiers = [tr for tr in self._tiers.values()
                          if tr.chunks_in_window == 0]
        for tr in idle_tiers:
            self._execute_parks(tr, bus)
        self._retry_admissions(bus)
        counts = qos.counts()
        bus.gauge("qos.limited_tenants", counts[QOS_LIMITED])
        bus.gauge("qos.parked_tenants", counts[QOS_PARKED])
        bus.gauge("qos.shed_tenants", counts[QOS_SHED])
        for tid, action, info in events:
            self._fire_qos(tid, action, info)

    def _execute_parks(self, tier: _Tier, bus) -> None:
        """Physically park every ``park_pending`` member of ``tier``
        (scheduler thread, window boundary only): snapshot the lane's
        summary AND raw running state host-side, final-save through the
        tenant's manager (the park is a durability point — the wire can
        ack everything folded so far), then free the lane. The freed
        width is reused by later admissions / un-parks; the next
        ``_maybe_reclaim`` can shrink the stack."""
        with self._lock:
            pend = [t for t in self._tenants.values()
                    if t.tier == tier.name and t.park_pending
                    and t.lane >= 0 and t.ready]
        if not pend:
            return
        batch = tier.batch
        retired: list = []
        with self._dispatch_lock:
            if batch.plan is None:
                with self._lock:
                    for t in pend:
                        t.lane = -1
                        t.park_pending = False
                return
            src = batch.state if batch.accum else batch.global_
            snap = batch.plan.snapshot(src)
            jax.block_until_ready(snap)
            parked_rows = {
                t.tid: jax.tree.map(
                    lambda l, lane=t.lane: np.asarray(l[lane]), snap)
                for t in pend
            }
            raw_rows = {
                t.tid: jax.tree.map(np.asarray, batch.slice_lane(t.lane))
                for t in pend
            }
            for t in pend:
                if t.manager is not None:
                    pos = t.consumed
                    t.manager.save(
                        batch.slice_lane(t.lane), pos,
                        meta={"tenant": str(t.tid), "tier": tier.name,
                              "window": tier.windows_closed,
                              "qos_parked": True},
                    )
                    retired.append((t.tid, pos))
            with self._lock:
                for t in pend:
                    t.parked = parked_rows[t.tid]
                    t.parked_state = raw_rows[t.tid]
                    t.parked_window = tier.windows_closed
                    t.lane = -1
                    t.park_pending = False
        for tid, pos in retired:
            self._notify_durable(tid, pos, bus)

    def _unpark_tenant(self, t: _Tenant) -> None:
        """Re-seat a parked tenant on a fresh lane, restoring the raw
        running state captured at park time bit-identically. Lane
        choice and assignment happen in ONE locked block so a
        concurrent ``admit()`` can never hand out the same lane."""
        with self._lock:
            if t.lane >= 0:
                return
            lane = 1 + max(
                (x.lane for x in self._tenants.values()
                 if x.tier == t.tier), default=-1,
            )
            t.lane = lane
            state = t.parked_state
        batch = self._tiers[t.tier].batch
        with self._dispatch_lock:
            batch.ensure_lanes(lane + 1)
            if state is not None:
                batch.set_lane(lane, state)
        # t.parked stays for query continuity until a window close
        # covers the new lane (the freshness guard in query()).
        self._work.set()

    def _shed_tenant(self, t: _Tenant, bus) -> int:
        """Close a tenant's stream: drop its queued (never-folded)
        chunks, mark it finished+shed. The folded prefix stays
        queryable from its parked/snapshot row; the wire maps this onto
        a typed NACK. Returns the dropped-chunk count."""
        with self._lock:
            dropped = len(t.queue)
            t.queue.clear()
            t.finished = True
            t.shed = True
            t.park_pending = False
        if dropped:
            bus.inc("qos.chunks_dropped", dropped)
        obs_bus.get_bus().watermarks.drop(t.tid)
        self._work.set()
        return dropped

    def _retry_admissions(self, bus) -> None:
        """Admit ONE queued tenant per QoS pass once active pressure is
        back under the ceiling (one at a time: each admission adds
        load, so the next pass re-reads pressure before the next
        waiter)."""
        qos = self.qos
        if qos.admission_ceiling_s is None:
            return
        with self._lock:
            if not self._qos_waiting:
                return
        if self._active_backlog_age() > qos.admission_ceiling_s:
            return
        with self._lock:
            if not self._qos_waiting:
                return
            tenant_id, tier, chunks = self._qos_waiting.popleft()
        try:
            lane = self.admit(tenant_id, tier, chunks=chunks)
        except ValueError:
            logger.exception(
                "queued admission for tenant %r failed", tenant_id)
            return
        if lane >= 0:
            bus.emit("qos.admissions_resumed", tenant=str(tenant_id))

    def _notify_durable(self, tenant_id, position: int, bus) -> None:
        """One tenant's durability point: retire the e2e ledger and
        fire the ``on_durable`` hooks (the router's checkpoint-gated
        wire acks). MUST be called outside the engine locks — hooks do
        socket writes."""
        telemetry = _telemetry_on()
        if self._wmk_on():
            bus.watermarks.retire_durable(
                tenant_id, position,
                bus=bus if telemetry else None,
                prefix=f"tenants.t{tenant_id}" if telemetry else None,
            )
        for fn in list(self.on_durable):
            try:
                fn(tenant_id, position)
            except Exception:
                logger.exception(
                    "on_durable hook failed for tenant %r at %d",
                    tenant_id, position,
                )

    def _fire_qos(self, tenant_id, action: str, info: dict) -> None:
        for fn in list(self.on_qos):
            try:
                fn(tenant_id, action, info)
            except Exception:
                logger.exception(
                    "on_qos hook failed for tenant %r (%s)",
                    tenant_id, action,
                )

    def _close_window(self, tier: _Tier, bus, tracer) -> None:
        batch = tier.batch
        plan = batch.plan
        t0 = tracer.now() if tracer is not None else 0.0
        with self._dispatch_lock:
            if batch.accum:
                snap = plan.snapshot(batch.state)
            else:
                merged = plan.merger(batch.state, batch.global_)
                if batch.agg.transient:
                    # Reference Merger transientState semantics: emit
                    # combine(window, global) then reset the global to
                    # the combine identity (init).
                    out = merged
                    batch.global_ = plan.init()
                else:
                    batch.global_ = merged
                    out = merged
                batch.state = plan.init()
                snap = plan.snapshot(out)
            # The window's one completion barrier (merge_emit analog):
            # the snapshot — and with it every fold of the window — is
            # ready before queries can observe the new window number.
            jax.block_until_ready(snap)
        tier.chunks_in_window = 0
        tier.windows_closed += 1
        bus.inc("tenants.windows_closed")
        # Lane bound from the snapshot's OWN leading dim, not
        # batch.lanes: an admission may widen the batch between the
        # snapshot compute and this publication.
        snap_lanes = jax.tree.leaves(snap)[0].shape[0]
        with self._lock:
            self.stats["windows_closed"] += 1
            tier.snapshot = snap
            tier.snapshot_lanes = snap_lanes
            tier.snapshot_window = tier.windows_closed
            for t in self._tenants.values():
                # A fresh snapshot covering an un-parked tenant's new
                # lane supersedes its parked row — drop the host copies
                # so queries read the live lane again.
                if (t.tier == tier.name and t.parked is not None
                        and 0 <= t.lane < snap_lanes):
                    t.parked = None
                    t.parked_state = None
        if tracer is not None:
            tracer.span("merge_emit", f"tenants/{tier.name}", t0,
                        tier=tier.name, window=tier.windows_closed)
        if (self.checkpoint_dir is not None
                and tier.windows_closed - tier.last_ckpt_window
                >= self.checkpoint_every):
            self._checkpoint_tier(tier)
        elif self.checkpoint_dir is None and (self._wmk_on()
                                              or self.on_durable):
            # No durability point configured: the window close IS the
            # retirement point — drain the tier's e2e ledgers (and fire
            # on_durable hooks) so the watermark tracks fold retirement
            # instead of growing forever. With a QoS controller this
            # MUST run even when telemetry is off, or every tenant's
            # backlog age grows without bound and parks the fleet.
            with self._lock:
                members = [(t.tid, t.consumed)
                           for t in self._tenants.values()
                           if t.tier == tier.name]
            for tid, pos in members:
                self._notify_durable(tid, pos, bus)
        if self.reclaim_after is not None:
            self._maybe_reclaim(tier, bus, tracer)
        if self.qos is not None:
            # Window boundary = the safe point for pending parks.
            self._execute_parks(tier, bus)

    def _checkpoint_tier(self, tier: _Tier) -> None:
        batch = tier.batch
        with self._dispatch_lock:
            if batch.plan is not None and batch.plan.flatten is not None:
                # Cadenced path flatten at checkpoint cadence (the
                # engine contract: bounded transform chase depth on
                # long streams; labels identical). The flattened stack
                # REPLACES the live state and is what the per-lane
                # snapshots slice.
                if batch.accum:
                    batch.state = batch.plan.flatten(batch.state)
                else:
                    batch.global_ = batch.plan.flatten(batch.global_)
            with self._lock:
                members = [
                    (t, t.consumed) for t in self._tenants.values()
                    if t.tier == tier.name and t.manager is not None
                    and t.lane >= 0
                ]
            saved: list = []
            for t, position in members:
                t_h = _time.perf_counter()
                t.manager.save(
                    batch.slice_lane(t.lane), position,
                    meta={"tenant": str(t.tid), "tier": tier.name,
                          "window": tier.windows_closed},
                )
                b = obs_bus.get_bus()
                obs_bus.publish_checkpoint(
                    b, "tenants", t.manager.path_for(position), t0=t_h,
                )
                saved.append((t.tid, position))
        tier.last_ckpt_window = tier.windows_closed
        # The per-tenant durability point: ingress→durable retires, the
        # low watermark advances and the router's checkpoint-gated wire
        # acks fire — OUTSIDE the dispatch lock (hooks do socket
        # writes; the saves above already dominate, so an ack can never
        # precede its durability).
        b = obs_bus.get_bus()
        for tid, position in saved:
            self._notify_durable(tid, position, b)

    def _maybe_reclaim(self, tier: _Tier, bus, tracer) -> None:
        """Idle-lane reclamation (called at every window close when
        ``reclaim_after`` is set): halve the tier's lane stack once the
        high-water LIVE lane count has stayed below width/2 for
        ``reclaim_after`` consecutive windows. Evicted (done) tenants'
        rows are snapshotted host-side — and final-checkpointed when a
        manager exists — BEFORE the stack is rebuilt, so their queries
        keep answering; live tenants compact into the low lanes."""
        batch = tier.batch
        with self._lock:
            members = [t for t in self._tenants.values()
                       if t.tier == tier.name]
            live_cnt = sum(1 for t in members
                           if not t.done and t.lane >= 0)
            hw = tier.hw_active
            tier.hw_active = live_cnt  # restart at the current floor
            width = batch.lanes
            target = batch._width_for(max(width // 2, live_cnt, 1))
            shrinkable = batch.plan is not None and target < width
            if shrinkable and 2 * hw < width:
                tier.low_windows += 1
            else:
                tier.low_windows = 0
            due = shrinkable and tier.low_windows >= self.reclaim_after
            if due:
                tier.low_windows = 0
        if not due:
            return
        with self._dispatch_lock:
            # Re-collect under the dispatch lock: an admission may have
            # widened/occupied lanes since the decision above.
            with self._lock:
                members = [t for t in self._tenants.values()
                           if t.tier == tier.name]
                if any(t.lane >= 0 and not t.ready for t in members):
                    # A half-admitted tenant holds a lane index admit()
                    # is still working against (its resume state lands
                    # under the dispatch lock, its readiness under the
                    # table lock — in that order): compacting lanes now
                    # would remap or drop the lane out from under it.
                    # Admission inserts the tenant (ready=False) in the
                    # same locked write that assigns the lane, so a
                    # reclaim seeing a consistent table here can never
                    # interleave with one — defer to the next window.
                    return
                live = sorted(
                    (t for t in members if not t.done and t.lane >= 0),
                    key=lambda t: t.lane,
                )
                evicted = [t for t in members if t.done and t.lane >= 0]
                width = batch.lanes
                target = batch._width_for(max(width // 2, len(live), 1))
                if batch.plan is None or target >= width:
                    return
            # Evicted lanes' state is snapshotted FIRST: the parked row
            # answers queries after the lane is gone, and the final
            # checkpoint makes the evicted tenant's exactly-once resume
            # point durable at its last dispatched chunk.
            src = batch.state if batch.accum else batch.global_
            snap = batch.plan.snapshot(src)
            jax.block_until_ready(snap)
            parked = {
                t.tid: jax.tree.map(
                    lambda l, _ln=t.lane: np.asarray(l[_ln]), snap
                )
                for t in evicted
            }
            final_saves: list = []
            for t in evicted:
                if t.manager is not None:
                    t.manager.save(
                        batch.slice_lane(t.lane), t.consumed,
                        meta={"tenant": str(t.tid), "tier": tier.name,
                              "window": tier.windows_closed,
                              "evicted": True},
                    )
                    final_saves.append((t.tid, t.consumed))
            keep_lanes = [t.lane for t in live]
            batch.shrink(keep_lanes, target)
            # Published snapshot rebuilt in the NEW lane order (fresher
            # than the last close, never staler), swapped in with the
            # lane remap in ONE locked write so queries never see a
            # remapped lane against the old stacked order.
            new_snap = None
            if keep_lanes:
                idx = np.asarray(keep_lanes)
                new_snap = jax.tree.map(lambda l: l[idx], snap)
                jax.block_until_ready(new_snap)
            freed = width - target
            with self._lock:
                for i, t in enumerate(live):
                    t.lane = i
                for t in evicted:
                    t.parked = parked[t.tid]
                    t.parked_window = tier.windows_closed
                    t.lane = -1
                if new_snap is not None:
                    tier.snapshot = new_snap
                    tier.snapshot_lanes = len(keep_lanes)
                    tier.snapshot_window = tier.windows_closed
                else:
                    # No live lanes kept: the old snapshot's lane order
                    # is meaningless now, and a later admission at lane
                    # 0 must not read an evicted tenant's row from it.
                    tier.snapshot = None
                    tier.snapshot_lanes = 0
                self.stats["reclaims"] += 1
                self.stats["lanes_reclaimed"] += freed
        # The evicted tenants' final saves are durability points too:
        # fire on_durable (router acks their folded tails) BEFORE
        # dropping the ledgers.
        for tid, pos in final_saves:
            self._notify_durable(tid, pos, bus)
        if self._wmk_on():
            # Evicted tenants fold nothing further: their e2e ledgers
            # (already drained to the final checkpoint) are dropped so
            # the max-backlog watermark never counts a parked row.
            for t in evicted:
                bus.watermarks.drop(t.tid)
        bus.inc("tenants.reclaims")
        bus.inc("tenants.lanes_reclaimed", freed)
        logger.info(
            "tier %r reclaimed %d idle lanes (width %d -> %d, %d "
            "evicted, %d live)", tier.name, freed, width, target,
            len(evicted), len(live),
        )
        if tracer is not None:
            tracer.instant("tenants.reclaim", tier=tier.name,
                           width=target, freed=freed,
                           evicted=len(evicted))

    def _flush_partial(self, bus, tracer) -> None:
        with self._lock:
            tiers = list(self._tiers.values())
        for tier in tiers:
            if tier.chunks_in_window:
                self._close_window(tier, bus, tracer)

    def _ensure_snapshots(self) -> None:
        # A tenant that resumed at end-of-stream folds zero new chunks
        # and closes no window; its restored summary must still be
        # queryable after drain() — snapshot the running state without
        # counting a window.
        with self._lock:
            tiers = [
                tier for tier in self._tiers.values()
                if tier.snapshot is None and tier.batch.plan is not None
                and any(t.tier == tier.name
                        for t in self._tenants.values())
            ]
        for tier in tiers:
            batch = tier.batch
            with self._dispatch_lock:
                src = batch.state if batch.accum else batch.global_
                snap = batch.plan.snapshot(src)
                jax.block_until_ready(snap)
            with self._lock:
                tier.snapshot = snap
                tier.snapshot_lanes = jax.tree.leaves(snap)[0].shape[0]

    def _final_checkpoints(self) -> None:
        if self.checkpoint_dir is None:
            return
        with self._lock:
            tiers = list(self._tiers.values())
        for tier in tiers:
            if tier.last_ckpt_window < tier.windows_closed:
                self._checkpoint_tier(tier)
