"""Coordinated multi-host recovery: checkpoint barriers, leader-elected
rotation, and degraded-capacity re-join.

The Flink reference delegates all of this to its runtime substrate
(PAPER.md L0: ``ListCheckpointed`` + coordinated snapshots — the
JobManager injects barriers, TaskManagers snapshot at the barrier, and a
checkpoint coordinator commits the global snapshot). Our re-owned
runtime (``engine/resilience.py``) checkpoints a single process; on a
``jax.distributed`` mesh each host would snapshot at an uncoordinated
chunk position and a single host loss would kill the whole stream. This
module re-owns the coordinator:

- **Checkpoint barrier** (:meth:`Coordinator.agree_position`): every
  host posts an *intent* carrying its last-retired-chunk position; the
  barrier resolves to ``max`` over all proposals (deterministic — every
  host computes it from the same intent set), and each host keeps
  folding its own partition until it retires the agreed position. All
  hosts therefore snapshot the SAME position, riding the existing
  position-header/CRC v2 checkpoint format unchanged.
- **Two-phase commit publish** (:meth:`Coordinator.publish`): each host
  writes its shard checkpoint into the epoch's ``host-<k>/`` directory
  (fsync'd tmp + atomic rename), then an atomic *prepared* marker; only
  when every host's marker is present does the leader atomically write
  ``MANIFEST.json`` naming the committed epoch. A host that dies
  mid-write leaves no prepared marker, the epoch never commits, and
  recovery reads the previous manifest — a mixed-epoch store is
  unreachable by construction and *rejected* if hand-assembled
  (:class:`MixedEpochError`).
- **Shared checkpoint store** (:class:`CheckpointStore`): a local/NFS
  directory today — ``epoch-<E>/host-<k>/ckpt-<pos>.npz`` per shard,
  one leader-written manifest, lease files under ``members/``. The
  layout is the API; a bucket-backed store slots in behind the same
  methods.
- **Leader election + rotation**: the lowest *live* process_index leads
  (liveness = lease files heartbeaten at ``lease_ttl/3`` cadence). A
  follower waiting for a commit that observes the leader's lease expire
  takes over the commit itself when it becomes the lowest live host —
  an epoch whose every shard is prepared always commits. Leadership
  changes are published on the obs event bus
  (``coordination.leader_elected``), so loss is observable and tested.
- **Restart-time re-join + the degradation rung**
  (:meth:`Coordinator.recover`): a restarted or replacement host
  validates the manifest, loads its shard leaves (CRC-checked), and
  re-enters the fold loop at the barrier-agreed position. On PERMANENT
  host loss the survivors re-shard the forest: the per-leaf checkpoint
  layout is host-agnostic, so each survivor adopts the orphan shards
  assigned to it (``old_host % new_count``) by folding them into its
  own state with the caller-supplied ``adopt`` combine — the stream
  continues at reduced capacity with a published
  ``coordination.degradations`` event instead of aborting. (Re-routing
  the lost host's *future* chunks is the ingest layer's job — the
  sharded-source-reader ROADMAP item; state adoption is owned here.)

Scope: coordination is restart-time (all hosts of an incarnation start
together, as under any pod launcher); barriers assume every host
retires the same chunk cadence over equal-length partitions — unequal
final positions are a loud :class:`CoordinationError`, never a silent
skew. Every wait is bounded (``barrier_timeout``) and fails fast when a
missing host's lease has expired. The ``"barrier"`` fault boundary
(``engine/faults.py``) fires inside :meth:`agree_position`,
:meth:`publish` and after the manifest write (path-carrying, so
``kind="corrupt"`` models a torn manifest), letting seeded FaultPlans
drive every failure path deterministically.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import re
import tempfile
import threading
import time
from typing import Any, Callable

from ..obs import bus as obs_bus
from . import faults as faults_mod
from .checkpoint import _fsync_dir, load_checkpoint, save_checkpoint

logger = logging.getLogger("gelly_tpu.coordination")

MANIFEST_VERSION = 1
MANIFEST_NAME = "MANIFEST.json"

_EPOCH_RE = re.compile(r"^epoch-(\d{8})$")
_HOST_RE = re.compile(r"^host-(\d+)$")


class CoordinationError(RuntimeError):
    """A coordination-protocol failure (always actionable text): a
    barrier that cannot complete, a dead peer, a commit that cannot
    happen. Never retried silently — a desynced mesh must surface."""


class ManifestCorruptError(CoordinationError):
    """MANIFEST.json is unreadable or fails schema validation. The
    manifest is written atomically, so a torn manifest means disk fault
    or tampering — rejected loudly, never guessed around."""


class MixedEpochError(CoordinationError):
    """The committed epoch's store is internally inconsistent: a shard
    is missing, or a shard/prepared position disagrees with the
    manifest. Unreachable via the 2PC protocol; a hand-assembled or
    bit-rotted store is rejected instead of resuming half an epoch."""


@dataclasses.dataclass(frozen=True)
class HostIdentity:
    """This process's slot in the coordinated group.

    Defaults come from the live jax.distributed state
    (:func:`detect_host_identity`); tests pass explicit identities so
    multiple in-process "hosts" can share one store.
    """

    process_index: int
    process_count: int
    coordinator_address: str | None = None

    def __post_init__(self):
        if self.process_count < 1:
            raise ValueError(
                f"process_count must be >= 1, got {self.process_count}"
            )
        if not (0 <= self.process_index < self.process_count):
            raise ValueError(
                f"process_index {self.process_index} out of range for "
                f"process_count {self.process_count}"
            )


def detect_host_identity() -> HostIdentity:
    """Identity from the live mesh state (``parallel/mesh.host_info``):
    single-process runs come back as ``HostIdentity(0, 1)``."""
    from ..parallel import mesh as mesh_lib

    info = mesh_lib.host_info()
    return HostIdentity(
        process_index=info["process_index"],
        process_count=info["process_count"],
        coordinator_address=info.get("coordinator_address"),
    )


# ---------------------------------------------------------------------- #
# atomic small-file helpers (same durability stance as engine/checkpoint)


def write_json_atomic(path: str, obj: dict) -> None:
    """tmp + fsync + rename: readers see the old content or the new,
    never a torn JSON."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".json.tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(obj, f, indent=1)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    _fsync_dir(d)


def _read_json(path: str) -> dict | None:
    """A JSON file's dict, or None when absent. Unparsable content
    returns None with a warning — rendezvous readers poll, so garbage
    (a fault-injected tear) surfaces as a bounded timeout, not a
    mis-agreement."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, json.JSONDecodeError) as e:
        logger.warning("unreadable coordination file %s: %s", path, e)
        return None
    return obj if isinstance(obj, dict) else None


# ---------------------------------------------------------------------- #
# the shared store


class CheckpointStore:
    """Path-per-host shared checkpoint store with a committed-epoch
    manifest.

    Layout under ``root``::

        MANIFEST.json                     # leader-written commit record
        epoch-<E>/intent-host-<k>.json    # barrier proposals
        epoch-<E>/host-<k>/ckpt-<pos>.npz # one shard per host per epoch
        epoch-<E>/prepared-host-<k>.json  # 2PC votes
        members/host-<k>.json             # lease heartbeats

    Every write is atomic (fsync'd tmp + rename); shard files are the
    unchanged v2 position-header/CRC format from ``engine/checkpoint``.
    """

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        os.makedirs(self.members_dir, exist_ok=True)

    # ------------------------------------------------------------ paths

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.root, MANIFEST_NAME)

    @property
    def members_dir(self) -> str:
        return os.path.join(self.root, "members")

    def epoch_dir(self, epoch: int) -> str:
        return os.path.join(self.root, f"epoch-{epoch:08d}")

    def host_dir(self, epoch: int, host: int) -> str:
        return os.path.join(self.epoch_dir(epoch), f"host-{host}")

    def shard_path(self, epoch: int, host: int, position: int) -> str:
        return os.path.join(
            self.host_dir(epoch, host), f"ckpt-{position:012d}.npz"
        )

    def _intent_path(self, epoch: int, host: int) -> str:
        return os.path.join(
            self.epoch_dir(epoch), f"intent-host-{host}.json"
        )

    def _prepared_path(self, epoch: int, host: int) -> str:
        return os.path.join(
            self.epoch_dir(epoch), f"prepared-host-{host}.json"
        )

    def list_epochs(self) -> list[int]:
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        out = []
        for n in names:
            m = _EPOCH_RE.match(n)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    # ---------------------------------------------------------- barrier

    def write_intent(self, epoch: int, host: int, position: int,
                     run_id: str | None = None) -> str:
        path = self._intent_path(epoch, host)
        write_json_atomic(path, {
            "host": host, "position": int(position), "epoch": epoch,
            "run_id": run_id,
        })
        return path

    def _read_host_records(self, epoch: int, prefix: str,
                           run_id: str | None,
                           process_count: int | None) -> dict[int, int]:
        """``{host: position}`` for every readable record of ``prefix``.
        ``run_id`` filters out records stamped by a DIFFERENT
        incarnation (a crashed run's leftovers in a re-attempted epoch
        dir); ``process_count`` drops records from host indices outside
        the CURRENT group (a permanently lost host's leftovers after a
        degraded re-join). None accepts everything (tests / manual
        surgery)."""
        out: dict[int, int] = {}
        d = self.epoch_dir(epoch)
        try:
            names = os.listdir(d)
        except OSError:
            return out
        for n in names:
            if not n.startswith(prefix):
                continue
            obj = _read_json(os.path.join(d, n))
            if obj is None or not isinstance(obj.get("position"), int):
                continue
            host = obj.get("host")
            if not isinstance(host, int) or isinstance(host, bool):
                # Parseable but malformed (bit-rot / hand edit): skip
                # like any unreadable record — garbage surfaces as a
                # bounded timeout, never an unhandled KeyError.
                continue
            if (run_id is not None and obj.get("run_id") is not None
                    and obj["run_id"] != run_id):
                continue
            if process_count is not None and not 0 <= host < process_count:
                continue
            out[host] = int(obj["position"])
        return out

    def read_intents(self, epoch: int, run_id: str | None = None,
                     process_count: int | None = None) -> dict[int, int]:
        """``{host: proposed_position}`` for every readable intent."""
        return self._read_host_records(
            epoch, "intent-host-", run_id, process_count
        )

    def clear_host_records(self, epoch: int, host: int) -> None:
        """Remove ONE host's rendezvous records (intent + vote) from an
        epoch dir — restart-time scrubbing of a crashed incarnation's
        leftovers. Own-records-only by contract: a peer's fresh record
        can never be this host's, so the scrub cannot race a faster
        peer's restart. Shard files stay (their content at a position
        is deterministic; re-attempts overwrite them atomically)."""
        for path in (self._intent_path(epoch, host),
                     self._prepared_path(epoch, host)):
            try:
                os.unlink(path)
            except OSError:
                pass

    # -------------------------------------------------------------- 2PC

    def write_shard(self, epoch: int, host: int, state,
                    position: int, meta: dict | None = None) -> str:
        path = self.shard_path(epoch, host, position)
        save_checkpoint(path, state, position=position, meta=meta)
        return path

    def write_prepared(self, epoch: int, host: int, position: int,
                       run_id: str | None = None) -> str:
        path = self._prepared_path(epoch, host)
        write_json_atomic(path, {
            "host": host, "position": int(position), "epoch": epoch,
            "run_id": run_id, "wall_time": time.time(),
        })
        return path

    def read_prepared(self, epoch: int, run_id: str | None = None,
                      process_count: int | None = None) -> dict[int, int]:
        """``{host: prepared_position}`` — the 2PC vote set."""
        return self._read_host_records(
            epoch, "prepared-host-", run_id, process_count
        )

    def commit(self, epoch: int, position: int, process_count: int,
               meta: dict | None = None) -> dict:
        """Atomically publish the manifest — THE commit point. Readers
        see the previous committed epoch or this one, never between.
        Returns the manifest dict that was written."""
        man = {
            "version": MANIFEST_VERSION,
            "epoch": epoch,
            "position": int(position),
            "process_count": process_count,
            "hosts": list(range(process_count)),
            "wall_time": time.time(),
            "meta": meta or {},
        }
        write_json_atomic(self.manifest_path, man)
        return man

    # --------------------------------------------------------- manifest

    def read_manifest(self) -> dict | None:
        """The committed manifest, or None when nothing ever committed.
        A present-but-unreadable or schema-invalid manifest raises
        :class:`ManifestCorruptError` — the commit record is written
        atomically, so garbage is a store fault, not a race."""
        try:
            with open(self.manifest_path) as f:
                raw = f.read()
        except FileNotFoundError:
            return None
        except OSError as e:
            raise ManifestCorruptError(
                f"manifest {self.manifest_path} unreadable: {e}"
            ) from e
        try:
            man = json.loads(raw)
        except json.JSONDecodeError as e:
            raise ManifestCorruptError(
                f"manifest {self.manifest_path} is torn/unparsable "
                f"({e}) — it is written atomically, so this is disk "
                "corruption, not an in-flight write"
            ) from e
        self.validate_manifest(man)
        return man

    def validate_manifest(self, man: Any) -> None:
        if not isinstance(man, dict):
            raise ManifestCorruptError(
                f"manifest {self.manifest_path}: expected an object, got "
                f"{type(man).__name__}"
            )
        version = man.get("version")
        if not isinstance(version, int) or version > MANIFEST_VERSION:
            raise ManifestCorruptError(
                f"manifest {self.manifest_path}: version {version!r} "
                f"(this build reads up to {MANIFEST_VERSION})"
            )
        for key, typ in (("epoch", int), ("position", int),
                         ("process_count", int), ("hosts", list),
                         ("wall_time", (int, float))):
            v = man.get(key)
            if not isinstance(v, typ) or isinstance(v, bool):
                raise ManifestCorruptError(
                    f"manifest {self.manifest_path}: field {key!r} is "
                    f"{v!r}; expected "
                    f"{typ.__name__ if isinstance(typ, type) else 'number'}"
                )
        if man["epoch"] < 0 or man["position"] < 0:
            raise ManifestCorruptError(
                f"manifest {self.manifest_path}: negative epoch/position"
            )
        if sorted(man["hosts"]) != list(range(man["process_count"])):
            raise ManifestCorruptError(
                f"manifest {self.manifest_path}: hosts {man['hosts']} do "
                f"not cover process_count {man['process_count']}"
            )

    def validate_epoch(self, man: dict) -> None:
        """Reject a mixed-epoch store: the committed epoch must hold a
        shard file at the manifest position for EVERY host it names.

        Validation targets the SHARDS, not the prepared markers: shards
        are fsync-durable before any vote is written and their content
        at a given position is deterministic, so they remain the truth
        even when a later crashed re-attempt of the same epoch
        overwrote the vote files — validating votes here could wedge a
        store whose shards are perfectly consistent. Votes are a
        commit-protocol artifact; once the manifest exists, they have
        served their purpose. (Per-shard position headers and CRCs are
        checked at load by ``load_shard``.)"""
        epoch, position = man["epoch"], man["position"]
        for host in man["hosts"]:
            shard = self.shard_path(epoch, host, position)
            if not os.path.exists(shard):
                raise MixedEpochError(
                    f"committed epoch {epoch}: host {host}'s shard at "
                    f"position {position} ({shard}) is missing — the "
                    "store mixes epochs (partial copy or manual "
                    "surgery?); refusing to resume from it"
                )

    def load_shard(self, epoch: int, host: int, position: int, like=None):
        """CRC-validated shard load → ``(state, position, meta)``."""
        return load_checkpoint(
            self.shard_path(epoch, host, position), like=like
        )

    def prune(self, committed: int, keep: int) -> None:
        """Leader-only epoch rotation: keep the committed epoch plus the
        ``keep - 1`` epochs directly below it (fallback forensics —
        older dirs include uncommitted leftovers from crashed
        incarnations, which can never commit since epoch numbers are
        monotone and never reused). Epochs ABOVE the committed one are
        never touched: one may be mid-write."""
        import shutil

        for e in self.list_epochs():
            if e < committed - (keep - 1):
                try:
                    # Vetted EO004 exception: the newest-artifact
                    # validation happened at the COMMIT point, not here
                    # — ``committed`` is the manifest epoch published by
                    # CheckpointStore.commit after the all-votes-in
                    # guard (the PI001 gate) and shard presence is
                    # re-verified by validate_manifest on every resume.
                    # Only epochs strictly below committed-(keep-1) are
                    # deleted; the committed epoch and everything above
                    # it (one may be mid-write) are never touched, so a
                    # torn in-flight epoch can never orphan the
                    # rotation.
                    shutil.rmtree(  # graphlint: disable=EO004
                        self.epoch_dir(e))
                except OSError:
                    pass


# ---------------------------------------------------------------------- #
# leases


class LeaseBoard:
    """Lease-file liveness: each host heartbeats
    ``members/host-<k>.json`` at ``ttl/3`` cadence; a host whose lease
    is older than ``ttl`` is expired. Wall-clock based — the hosts of a
    store share a machine or a fleet with sane NTP; the ttl is seconds,
    not milliseconds."""

    def __init__(self, store: CheckpointStore, host: int, ttl: float,
                 clock: Callable[[], float] = time.time):
        if ttl <= 0:
            raise ValueError(f"lease ttl must be > 0, got {ttl}")
        self.store = store
        self.host = host
        self.ttl = ttl
        self._clock = clock
        self._last_beat = 0.0
        # beat() is called from BOTH the background lease thread and the
        # protocol paths (maybe_beat per chunk, forced beats at barrier
        # entry): the rate-limit check-then-set and the beats counter
        # are a lost-update read-modify-write without this lock
        # (racecheck RC001/RC002).
        self._beat_lock = threading.Lock()
        # Incarnation boundary for expiry: only a lease beaten AT OR
        # AFTER this board existed counts as "seen alive"; an older
        # file is a previous incarnation's leftover and reads as
        # not-joined-yet (which waits, bounded), never as death — else
        # a restart whose peers construct a beat slower would
        # false-abort its first barrier on stale files.
        self.born = clock()
        self.beats = 0

    def _path(self, host: int) -> str:
        return os.path.join(self.store.members_dir, f"host-{host}.json")

    def beat(self, force: bool = False) -> bool:
        """Refresh this host's lease (rate-limited to ttl/3); returns
        True when a write actually happened."""
        now = self._clock()
        with self._beat_lock:
            if not force and now - self._last_beat < self.ttl / 3.0:
                return False
            self._last_beat = now
            self.beats += 1
            beats = self.beats
        # The fsync'd file write stays OUTSIDE the lock: serializing the
        # beat thread against a barrier's forced beat on a slow (NFS)
        # store would make liveness wait on disk latency (the same
        # discipline racecheck RC004 enforces). Concurrent force-beats
        # both write — write_json_atomic is rename-atomic, last wins.
        write_json_atomic(self._path(self.host), {
            "host": self.host, "wall_time": now, "ttl": self.ttl,
            "beats": beats,
        })
        return True

    def wall(self, host: int) -> float | None:
        obj = _read_json(self._path(host))
        if obj is None:
            return None
        w = obj.get("wall_time")
        return float(w) if isinstance(w, (int, float)) else None

    def expired(self, host: int) -> bool:
        """True only for a host seen alive DURING THIS INCARNATION
        (lease beaten at/after this board's construction) that then let
        its lease lapse. An absent file — or a stale leftover from a
        previous incarnation — is "not joined yet", which waits
        (bounded by the caller's timeout) rather than failing fast."""
        w = self.wall(host)
        return (w is not None and w >= self.born
                and self._clock() - w > self.ttl)

    def live(self) -> set[int]:
        """Hosts with a fresh lease."""
        out = set()
        try:
            names = os.listdir(self.store.members_dir)
        except OSError:
            return out
        now = self._clock()
        for n in names:
            m = _HOST_RE.match(n.removesuffix(".json"))
            if not m:
                continue
            obj = _read_json(os.path.join(self.store.members_dir, n))
            if obj is None:
                continue
            w = obj.get("wall_time")
            if isinstance(w, (int, float)) and now - w <= self.ttl:
                out.add(int(m.group(1)))
        return out


# ---------------------------------------------------------------------- #
# the coordinator


@dataclasses.dataclass(frozen=True)
class CoordinationConfig:
    """Knobs of :class:`Coordinator` (all have production defaults).

    ``lease_thread`` (default True) runs a daemon thread beating this
    host's lease every ``lease_ttl / 3`` for the coordinator's
    lifetime: the lease then means PROCESS liveness (a SIGKILLed host
    expires, a host stalled in a long shard write / jit compile does
    not), so peers never false-declare a slow-but-alive host dead.
    Protocol *progress* hangs are still bounded by
    ``barrier_timeout``. Tests that simulate silent death set it False
    (or ``close()`` the coordinator, which stops the thread).
    """

    lease_ttl: float = 5.0
    poll_s: float = 0.02
    barrier_timeout: float = 60.0
    keep_epochs: int = 3
    lease_thread: bool = True
    sleep: Callable[[float], None] = time.sleep
    clock: Callable[[], float] = time.monotonic


class Coordinator:
    """One host's handle on the coordinated-recovery protocol.

    Construct one per process over a shared ``root`` (all hosts must
    see the same directory). The resilient driver
    (``engine/resilience.ResilientRunner(coordinator=...)``) calls
    :meth:`agree_position` at checkpoint cadence, :meth:`publish` when
    the barrier position is retired, :meth:`recover` at start, and
    :meth:`maybe_beat` per chunk; all four are equally usable
    standalone.
    """

    def __init__(self, root: str, identity: HostIdentity | None = None,
                 config: CoordinationConfig | None = None):
        self.identity = identity or detect_host_identity()
        self.config = config or CoordinationConfig()
        self.store = CheckpointStore(root)
        self.board = LeaseBoard(
            self.store, self.identity.process_index,
            self.config.lease_ttl,
        )
        self._last_leader: int | None = None
        self._last_observe = float("-inf")
        self.committed_epoch: int | None = None
        self.committed_position: int | None = None
        man = self.store.read_manifest()
        self._reset_epochs(man)
        self.board.beat(force=True)
        self._observe_leader()
        self._beat_stop = threading.Event()
        if self.config.lease_thread:
            t = threading.Thread(
                target=self._beat_loop, daemon=True,
                name=f"gelly-lease-{self.identity.process_index}",
            )
            t.start()
        _register(self)

    def _reset_epochs(self, man: dict | None) -> None:
        """Derive epoch numbering from the COMMITTED state only:
        ``committed + 1``. Every host of an incarnation reads the same
        manifest, so the numbering agrees even when a fast host reaches
        its first barrier before a slow host finishes constructing
        (listing live epoch dirs here would race exactly there). A
        crashed incarnation's uncommitted epoch dir is therefore
        RE-ATTEMPTED in place — safe because every write into it is an
        atomic per-host overwrite, and stale files from a *different*
        incarnation are filtered by ``run_id`` (below)."""
        if man is not None:
            self.committed_epoch = man["epoch"]
            self.committed_position = man["position"]
        committed = man["epoch"] if man is not None else 0
        self._next_epoch = committed + 1
        # Shared incarnation tag: all hosts restart together
        # (restart-time coordination) and read the same manifest, so
        # they derive the same run_id; intents/votes left by a PREVIOUS
        # incarnation that started from a DIFFERENT committed epoch
        # carry a different tag and are ignored by the rendezvous
        # readers. Incarnations that crashed without advancing the
        # committed epoch share the tag — so additionally every host
        # SCRUBS ITS OWN records from epochs above the committed one
        # here (own files only: a peer's fresh record can never be
        # ours, so this cannot race a faster peer's restart), and the
        # rendezvous readers drop out-of-group host indices (a lost
        # host's leftovers after a degraded re-join). The residual
        # window — a host so fast it barriers before a slow peer's
        # scrub — can at worst skew one barrier into a LOUD
        # deadline-bounded abort (skewed votes are never committed);
        # by the next restart the scrub has run everywhere and the
        # attempt converges.
        wall = man["wall_time"] if man is not None else 0
        self._run_id = f"e{committed}-{wall}"
        for e in self.store.list_epochs():
            if e > committed:
                self.store.clear_host_records(e, self.process_index)

    def _beat_loop(self) -> None:
        while not self._beat_stop.wait(self.config.lease_ttl / 3.0):
            try:
                self.board.beat(force=True)
            except Exception:  # noqa: BLE001 — liveness must not crash
                logger.exception("lease beat failed")

    # ------------------------------------------------------- liveness

    @property
    def process_index(self) -> int:
        return self.identity.process_index

    def maybe_beat(self) -> None:
        """Per-chunk liveness hook: rate-limited lease refresh plus a
        leadership observation at ``lease_ttl / 3`` cadence. The
        observation has its OWN rate limiter — with the background
        lease thread on, ``beat()`` here almost never fires (the thread
        keeps the lease fresh), but leadership changes must still
        surface between barriers."""
        self.board.beat()
        now = self.config.clock()
        if now - self._last_observe >= self.config.lease_ttl / 3.0:
            self._last_observe = now
            self._observe_leader()

    def _observe_leader(self) -> int | None:
        live = self.board.live()
        live.add(self.process_index)  # own lease is fresh by definition
        leader = min(live)
        if leader != self._last_leader:
            obs_bus.get_bus().emit(
                "coordination.leader_elected",
                leader=leader, previous=self._last_leader,
                host=self.process_index,
                live=sorted(live),
            )
            logger.info(
                "host %d observes leader %s (was %s; live=%s)",
                self.process_index, leader, self._last_leader,
                sorted(live),
            )
            self._last_leader = leader
        return leader

    @property
    def is_leader(self) -> bool:
        return self._last_leader == self.process_index

    # -------------------------------------------------------- barrier

    def agree_position(self, position: int) -> tuple[int, int]:
        """Checkpoint barrier: post this host's last-retired position,
        wait for every host's proposal, return ``(epoch, agreed)`` with
        ``agreed = max(proposals) >= position``. Each host then folds
        to ``agreed`` and calls :meth:`publish`."""
        epoch = self._next_epoch
        self._next_epoch += 1
        n = self.identity.process_count
        # Entering the barrier proves liveness — force a beat so a long
        # host-side stall right before (first-dispatch jit compiles, a
        # slow fold) can't read as death to a peer's expiry check.
        self.board.beat(force=True)
        path = self.store.write_intent(
            epoch, self.process_index, position, run_id=self._run_id
        )
        faults_mod.inject("barrier", path=path)
        intents = self._wait(
            lambda: self.store.read_intents(
                epoch, run_id=self._run_id, process_count=n
            ),
            lambda got: len(got) >= n,
            what=f"barrier epoch {epoch}: intents",
        )
        agreed = max(intents.values())
        # t_wall: wall-clock stamp for the multi-host trace stitcher —
        # per-host rings use monotonic clocks with unrelated epochs, so
        # stitch_traces aligns on this instant (matched by `epoch`) and
        # t_wall is the recorded fallback evidence of the true skew.
        obs_bus.get_bus().emit(
            "coordination.barrier_agreed", epoch=epoch, position=agreed,
            host=self.process_index, proposals=len(intents),
            t_wall=round(time.time(), 6),
        )
        return epoch, agreed

    # ------------------------------------------------------------ 2PC

    def publish(self, epoch: int, state, position: int,
                meta: dict | None = None) -> dict:
        """Two-phase commit of this host's shard at the barrier-agreed
        position: write the shard (phase 1: prepared), then drive or
        await the manifest commit (phase 2). Returns the committed
        manifest. If the leader dies between the phases, the next
        lowest live host takes the commit over — rotation, not abort."""
        faults_mod.inject("barrier")
        # The shard write (device_get'd state → fsync'd file) can stall
        # past ttl on big summaries; prove liveness on entry.
        self.board.beat(force=True)
        self.store.write_shard(
            epoch, self.process_index, state, position, meta=meta
        )
        self.store.write_prepared(
            epoch, self.process_index, position, run_id=self._run_id
        )
        obs_bus.get_bus().emit(
            "coordination.prepared", epoch=epoch, position=position,
            host=self.process_index,
        )
        man = self._drive_commit(epoch, position)
        self.committed_epoch = man["epoch"]
        self.committed_position = man["position"]
        return man

    def _drive_commit(self, epoch: int, position: int) -> dict:
        cfg = self.config
        deadline = cfg.clock() + cfg.barrier_timeout
        leader = self._last_leader
        next_liveness = cfg.clock()  # first iteration observes at once
        while True:
            man = self.store.read_manifest()
            if man is not None and man["epoch"] >= epoch:
                if man["epoch"] == epoch and man["position"] != position:
                    raise CoordinationError(
                        f"epoch {epoch} committed at position "
                        f"{man['position']} but this host prepared "
                        f"{position} — barrier skew"
                    )
                return man
            now = cfg.clock()
            if now >= next_liveness:
                # Leadership/expiry move at lease granularity; see _wait.
                next_liveness = now + cfg.lease_ttl / 3.0
                leader = self._observe_leader()
                self.board.beat()
            if leader == self.process_index:
                committed = self._leader_commit(epoch, position)
                if committed is not None:
                    return committed
            if now > deadline:
                raise CoordinationError(
                    f"epoch {epoch}: no commit within "
                    f"{cfg.barrier_timeout:.3g}s (leader {leader}, "
                    f"live {sorted(self.board.live())})"
                )
            cfg.sleep(cfg.poll_s)

    def _leader_commit(self, epoch: int, position: int) -> dict | None:
        """Leader side of phase 2 (non-blocking — the deadline lives in
        ``_drive_commit``'s loop): once every host's prepared marker is
        present, write the manifest atomically and prune old epochs.
        Returns None while votes are still (live-host) pending; raises
        when a missing host is provably dead — the epoch aborts with no
        manifest, so recovery uses the previous committed epoch."""
        n = self.identity.process_count
        prepared = self.store.read_prepared(
            epoch, run_id=self._run_id, process_count=n
        )
        # A vote at the wrong position is treated as PENDING, never
        # committed: it is either a crashed incarnation's leftover that
        # its live host will overwrite in a moment (converges), or a
        # genuine barrier-skew bug — then the commit deadline in
        # _drive_commit expires and the epoch aborts loudly. Raising
        # here instantly would turn the benign leftover race into an
        # abort on every re-attempt.
        skew = {h: p for h, p in prepared.items() if p != position}
        if skew:
            logger.warning(
                "epoch %d: prepared positions %s disagree with the "
                "barrier position %d; waiting for overwrite (stale "
                "leftover?) under the commit deadline", epoch, skew,
                position,
            )
        missing = (set(range(n)) - prepared.keys()) | skew.keys()
        if missing:
            dead = sorted(
                h for h in missing - skew.keys()
                if self.board.expired(h)
            )
            if dead:
                raise CoordinationError(
                    f"epoch {epoch} cannot commit: host(s) {dead} died "
                    "before preparing their shard — aborting the epoch "
                    "(no manifest written; recovery uses epoch "
                    f"{self.committed_epoch})"
                )
            return None
        man = self.store.commit(
            epoch, position, n,
            meta={"committed_by": self.process_index},
        )
        # Path-carrying injection point AFTER the atomic write: a
        # kind="corrupt" fault here models the torn manifest recovery
        # must reject.
        faults_mod.inject("barrier", path=self.store.manifest_path)
        obs_bus.get_bus().emit(
            "coordination.committed", epoch=epoch, position=position,
            host=self.process_index,
        )
        self.store.prune(epoch, self.config.keep_epochs)
        return man

    # -------------------------------------------------------- recover

    def recover(self, like=None, adopt: Callable | None = None,
                reshard: Callable | None = None):
        """Restart-time re-join. Returns ``None`` (fresh store) or
        ``(state, position, meta)``:

        - committed ``process_count`` == ours: validate the epoch
          (:class:`MixedEpochError` on inconsistency), load OUR shard
          (CRC-checked against ``like``), publish a
          ``coordination.rejoins`` event.
        - committed ``process_count`` > ours and ``adopt`` given:
          the degradation rung — this survivor additionally loads every
          orphan shard assigned to it (``old_host % new_count``) and
          folds each into its state with ``adopt(state, shard_state)``;
          publishes ``coordination.degradations``. ``reshard`` — the
          ingest-side re-shard hook (``gelly_tpu.ingest.
          ShardRoutingTable.reroute`` fits it) — is then called with
          ``(old_count, new_count)`` so the lost hosts' future chunks
          follow their adopted state to the same survivors.
        - committed ``process_count`` > ours without ``adopt``: loud
          :class:`CoordinationError` — silently dropping shards would
          lose folded edges.
        - committed ``process_count`` < ours (the group GREW): hosts
          below the old count load their shard; new hosts return
          ``(None, position, meta)`` — fresh state, barrier-agreed
          position.

        ``state`` can be ``None`` only in that last case.
        """
        man = self.store.read_manifest()
        self._reset_epochs(man)
        if man is None:
            return None
        self.store.validate_epoch(man)
        epoch, position = man["epoch"], man["position"]
        me, n = self.process_index, self.identity.process_count
        old_n = man["process_count"]
        bus = obs_bus.get_bus()
        if old_n > n and adopt is None:
            raise CoordinationError(
                f"manifest epoch {epoch} holds {old_n} host shards but "
                f"only {n} host(s) are re-joining and no adopt combine "
                "was supplied — refusing to silently drop "
                f"{old_n - n} shard(s) of folded state"
            )
        state = None
        adopted: list[int] = []
        if me < old_n:
            state, pos, meta = self.store.load_shard(
                epoch, me, position, like=like
            )
            if pos != position:
                raise MixedEpochError(
                    f"epoch {epoch}: own shard records position {pos} "
                    f"but the manifest commits {position}"
                )
        else:
            meta = dict(man.get("meta", {}))
        if old_n > n:
            # Degraded-capacity takeover: orphan host j -> survivor
            # j % n. The per-leaf layout is host-agnostic, so adopting
            # a shard is one combine per orphan.
            for j in range(old_n):
                if j < n or j % n != me:
                    continue
                s_j, pos_j, _ = self.store.load_shard(
                    epoch, j, position, like=like
                )
                if pos_j != position:
                    raise MixedEpochError(
                        f"epoch {epoch}: orphan shard {j} records "
                        f"position {pos_j} vs manifest {position}"
                    )
                state = s_j if state is None else adopt(state, s_j)
                adopted.append(j)
            bus.emit(
                "coordination.degradations",
                epoch=epoch, position=position,
                lost_hosts=old_n - n, process_count=n,
                previous_process_count=old_n,
                adopted=adopted, host=me,
                capacity_frac=round(n / old_n, 4),
            )
            logger.warning(
                "host %d re-joins DEGRADED: %d of %d hosts survive "
                "(adopted shards %s); stream continues at %.0f%% capacity",
                me, n, old_n, adopted, 100.0 * n / old_n,
            )
            if reshard is not None:
                # Ingest follows state: re-route the lost hosts' reader
                # shards to the survivors that adopted their forests
                # (same j % new_count rule on both sides).
                reshard(old_n, n)
        bus.emit(
            "coordination.rejoins", epoch=epoch, position=position,
            host=me, degraded=bool(adopted),
        )
        return state, position, meta

    # ----------------------------------------------------- rendezvous

    def _wait(self, read: Callable[[], dict], ready: Callable[[dict], bool],
              what: str) -> dict:
        """Bounded poll for a rendezvous set keyed by host index: fails
        FAST when a missing host's lease has provably expired (peer
        death), else at ``barrier_timeout``. Keeps this host's own
        lease fresh while it waits."""
        cfg = self.config
        deadline = cfg.clock() + cfg.barrier_timeout
        n = self.identity.process_count
        next_liveness = cfg.clock()  # first iteration checks immediately
        while True:
            got = read()
            if ready(got):
                return got
            missing = set(range(n)) - set(got)
            now = cfg.clock()
            if now >= next_liveness:
                # Expiry/leadership move at lease granularity — probing
                # the members dir every poll_s would be pure metadata
                # churn on a shared (NFS) store for identical answers.
                next_liveness = now + cfg.lease_ttl / 3.0
                dead = sorted(h for h in missing if self.board.expired(h))
                if dead:
                    raise CoordinationError(
                        f"{what}: host(s) {dead} lease-expired while "
                        f"{sorted(missing)} still missing — peer death"
                    )
                self.board.beat()
                self._observe_leader()
            if now > deadline:
                raise CoordinationError(
                    f"{what}: incomplete after "
                    f"{cfg.barrier_timeout:.3g}s (missing "
                    f"{sorted(missing)}, live {sorted(self.board.live())})"
                )
            cfg.sleep(cfg.poll_s)

    def close(self) -> None:
        """Stop the lease beat thread (this host's lease then expires
        within ``lease_ttl`` — peers treat it as departed) and drop the
        observability registration. Idempotent; a closed coordinator
        must not be reused — construct a fresh one per incarnation."""
        self._beat_stop.set()
        _unregister(self)


# ---------------------------------------------------------------------- #
# active-coordinator registry (observability: heartbeat/trace host lines)

_ACTIVE: Coordinator | None = None
_ACTIVE_LOCK = threading.Lock()


def _register(coord: Coordinator) -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = coord


def _unregister(coord: Coordinator) -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        if _ACTIVE is coord:
            _ACTIVE = None


def active_coordinator() -> Coordinator | None:
    return _ACTIVE


def leader_flag() -> bool | None:
    """This process's last-observed leadership, or None when no
    coordinator is active — the heartbeat/trace host-identity field."""
    coord = _ACTIVE
    return coord.is_leader if coord is not None else None
