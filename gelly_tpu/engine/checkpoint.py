"""Checkpoint / resume of summary state.

The reference's only checkpoint hook is ``Merger implements ListCheckpointed``:
``snapshotState`` returns ``[summary]`` and ``restoreState`` reads it back
(``M/SummaryAggregation.java:127-135``) — the summary *is* the checkpoint
payload. Same stance here: a checkpoint is the device→host snapshot of the
global summary pytree plus the stream position (chunks consumed), written
atomically; resume reloads the arrays and continues folding from that
position.

Format: ``.npz`` with flattened leaves + a JSON header describing the pytree
structure — no pickle, so checkpoints are portable and inspectable.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any

import jax
import numpy as np


def save_checkpoint(path: str, summary, position: int = 0,
                    meta: dict | None = None) -> None:
    """Atomically write ``summary`` (any pytree of arrays) + stream position."""
    leaves, treedef = jax.tree.flatten(summary)
    header = {
        "treedef": str(treedef),
        "num_leaves": len(leaves),
        "position": int(position),
        "meta": meta or {},
    }
    arrays = {f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)}
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, __header__=np.frombuffer(
                json.dumps(header).encode(), dtype=np.uint8
            ), **arrays)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_checkpoint(path: str, like=None):
    """Load a checkpoint. Returns ``(summary, position, meta)``.

    ``like`` — a template pytree with the same structure (e.g. ``agg.init()``);
    required to rebuild structured summaries. When None, returns the flat leaf
    list in saved order.
    """
    with np.load(path) as z:
        header = json.loads(bytes(z["__header__"]).decode())
        leaves = [z[f"leaf_{i}"] for i in range(header["num_leaves"])]
    if like is not None:
        _, treedef = jax.tree.flatten(like)
        if treedef.num_leaves != len(leaves):
            raise ValueError(
                f"checkpoint has {len(leaves)} leaves; template has "
                f"{treedef.num_leaves}"
            )
        summary = jax.tree.unflatten(treedef, leaves)
    else:
        summary = leaves
    return summary, header["position"], header["meta"]
