"""Checkpoint / resume of summary state.

The reference's only checkpoint hook is ``Merger implements ListCheckpointed``:
``snapshotState`` returns ``[summary]`` and ``restoreState`` reads it back
(``M/SummaryAggregation.java:127-135``) — the summary *is* the checkpoint
payload. Same stance here: a checkpoint is the device→host snapshot of the
global summary pytree plus the stream position (chunks consumed), written
atomically; resume reloads the arrays and continues folding from that
position.

Format: ``.npz`` with flattened leaves + a JSON header describing the pytree
structure — no pickle, so checkpoints are portable and inspectable. Format
version 2 adds a per-leaf CRC32 so a torn or bit-rotted file is detected at
load (``CheckpointCorruptError``) instead of unflattening garbage into the
next jit; version-1 files (no ``version`` key) still load, without the CRC
check. Files claiming a version newer than :data:`CHECKPOINT_VERSION` are
rejected loudly — schema skew, not corruption.
"""

from __future__ import annotations

import json
import os
import tempfile
import zipfile
import zlib
from typing import Any

import jax
import numpy as np

# Bump when the on-disk schema changes incompatibly. v1 = no version key,
# no CRCs; v2 = per-leaf crc32 list in the header.
CHECKPOINT_VERSION = 2

# Positions beyond this are nonsense (2^53: exact-integer float range, and
# far past any real chunk count) — treat as corruption, not data.
_MAX_POSITION = 1 << 53


class CheckpointCorruptError(ValueError):
    """The checkpoint file is unreadable, torn, or fails validation.

    Subclasses ValueError so pre-existing ``except ValueError`` callers
    keep working; recovery code (``engine/resilience.py``) catches this to
    fall back to the previous checkpoint in the rotation.
    """


def _fsync_dir(path: str) -> None:
    """Best-effort directory fsync — makes the rename itself durable.
    Some filesystems reject O_RDONLY directory fsync; that is their
    durability model, not an error this layer can act on."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def save_checkpoint(path: str, summary, position: int = 0,
                    meta: dict | None = None, fsync: bool = True) -> dict:
    """Atomically AND durably write ``summary`` (any pytree of arrays)
    plus the stream position: tmp file → fsync → rename → directory
    fsync. Readers see the previous checkpoint or this one in full,
    never a torn file — and after return the bytes are on the platter,
    so a kernel crash cannot resurrect a pre-write view after rotation
    has pruned the fallback. ``fsync=False`` skips both syncs for
    throwaway stores (tests that measure cadence, not durability).
    Returns the written header dict (rotation cross-checks its CRC
    list against the on-disk header without re-reading the payload)."""
    if position < 0:
        raise ValueError(f"checkpoint position must be >= 0, got {position}")
    leaves, treedef = jax.tree.flatten(summary)
    arrays = {f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)}
    header = {
        "version": CHECKPOINT_VERSION,
        "treedef": str(treedef),
        "num_leaves": len(leaves),
        "position": int(position),
        "meta": meta or {},
        "crc32": [
            zlib.crc32(np.ascontiguousarray(a).tobytes())
            for a in arrays.values()
        ],
    }
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    # The tmp name carries the target basename so a crashed writer's
    # leftover is attributable: CheckpointManager reaps stale tmps by
    # ROTATION PREFIX at takeover, and an anonymous mkstemp name would
    # make one rotation's cleanup delete another's in-flight write in
    # a shared directory.
    base = os.path.basename(path)
    stem = base[: -len(".npz")] if base.endswith(".npz") else base
    fd, tmp = tempfile.mkstemp(dir=d, prefix=stem + "-", suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, __header__=np.frombuffer(
                json.dumps(header).encode(), dtype=np.uint8
            ), **arrays)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    if fsync:
        _fsync_dir(d)
    return header


def read_checkpoint_header(path: str) -> dict:
    """Parse ONLY the ``__header__`` entry (schema version, position,
    per-leaf CRC list) — a few-KB read. A torn/truncated file fails
    here (the zip central directory lives at EOF), wrapped as
    :class:`CheckpointCorruptError`; used by rotation to cross-check a
    just-written file against the CRCs computed during the write
    without re-reading the whole payload."""
    try:
        with np.load(path) as z:
            header = json.loads(bytes(z["__header__"]).decode())
    except (zipfile.BadZipFile, KeyError, OSError, ValueError,
            json.JSONDecodeError, zlib.error, EOFError) as e:
        raise CheckpointCorruptError(
            f"checkpoint {path} header unreadable (torn write?): {e}"
        ) from e
    if not isinstance(header, dict):
        raise CheckpointCorruptError(
            f"checkpoint {path}: header is {type(header).__name__}, "
            "expected an object"
        )
    return header


def _validate_leaf(i: int, arr: np.ndarray, template, path: str) -> None:
    t_shape = tuple(np.shape(template))
    if tuple(arr.shape) != t_shape:
        raise CheckpointCorruptError(
            f"checkpoint {path}: leaf {i} has shape {tuple(arr.shape)} but "
            f"the template expects {t_shape}"
        )
    t_dtype = getattr(template, "dtype", None)
    if t_dtype is not None and np.dtype(arr.dtype) != np.dtype(t_dtype):
        raise CheckpointCorruptError(
            f"checkpoint {path}: leaf {i} has dtype {arr.dtype} but the "
            f"template expects {np.dtype(t_dtype)}"
        )


def load_checkpoint(path: str, like=None):
    """Load a checkpoint. Returns ``(summary, position, meta)``.

    ``like`` — a template pytree with the same structure (e.g. ``agg.init()``);
    required to rebuild structured summaries. When None, returns the flat leaf
    list in saved order. Every leaf is validated against the template's
    shape/dtype (a same-leaf-count but wrong-shaped checkpoint would
    otherwise unflatten silently and fail later inside jit) and, for
    version-2 files, against its stored CRC32. Torn/unparseable files raise
    :class:`CheckpointCorruptError`.
    """
    try:
        with np.load(path) as z:
            header = json.loads(bytes(z["__header__"]).decode())
            version = header.get("version", 1)
            if version > CHECKPOINT_VERSION:
                raise CheckpointCorruptError(
                    f"checkpoint {path} has format version {version}; this "
                    f"build reads up to {CHECKPOINT_VERSION} — written by a "
                    "newer gelly_tpu?"
                )
            leaves = [z[f"leaf_{i}"] for i in range(header["num_leaves"])]
    except FileNotFoundError:
        raise
    except CheckpointCorruptError:
        raise
    except (zipfile.BadZipFile, KeyError, OSError, ValueError,
            json.JSONDecodeError, zlib.error, EOFError) as e:
        raise CheckpointCorruptError(
            f"checkpoint {path} is unreadable (torn write?): {e}"
        ) from e
    position = header.get("position")
    if (not isinstance(position, int) or isinstance(position, bool)
            or position < 0 or position > _MAX_POSITION):
        raise CheckpointCorruptError(
            f"checkpoint {path} records position {position!r}; expected an "
            f"integer in [0, {_MAX_POSITION}]"
        )
    crcs = header.get("crc32")
    if crcs is not None:
        if len(crcs) != len(leaves):
            raise CheckpointCorruptError(
                f"checkpoint {path}: {len(crcs)} CRCs for "
                f"{len(leaves)} leaves"
            )
        for i, (arr, want) in enumerate(zip(leaves, crcs)):
            got = zlib.crc32(np.ascontiguousarray(arr).tobytes())
            if got != want:
                raise CheckpointCorruptError(
                    f"checkpoint {path}: leaf {i} CRC mismatch "
                    f"(stored {want:#010x}, computed {got:#010x}) — "
                    "corrupt or torn file"
                )
    meta = header.get("meta", {})
    if not isinstance(meta, dict):
        raise CheckpointCorruptError(
            f"checkpoint {path} records meta of type "
            f"{type(meta).__name__}; expected a dict"
        )
    if like is not None:
        t_leaves, treedef = jax.tree.flatten(like)
        if treedef.num_leaves != len(leaves):
            raise ValueError(
                f"checkpoint has {len(leaves)} leaves; template has "
                f"{treedef.num_leaves}"
            )
        for i, (arr, tmpl) in enumerate(zip(leaves, t_leaves)):
            _validate_leaf(i, arr, tmpl, path)
        summary = jax.tree.unflatten(treedef, leaves)
    else:
        summary = leaves
    return summary, position, meta
