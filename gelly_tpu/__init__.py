"""gelly_tpu — TPU-native single-pass graph-stream analytics.

A from-scratch JAX/XLA/Pallas framework with the capabilities of
``ZhouJiaLinmumu/gelly-streaming`` (Flink's experimental graph-streaming API):
unbounded edge streams folded into compact mergeable summaries,
partition-parallel across a TPU mesh with ICI collective merges.

Layer map (mirrors SURVEY.md §1):
  core/      EdgeChunk substrate, ingestion, EdgeStream API, windows
  engine/    SummaryAggregation plugin contract + bulk/tree runners
  ops/       device kernels: union-find, segment ops, hash set, triangles
  parallel/  mesh, hash partitioning, collective merge primitives
  library/   one-pass algorithms (CC, bipartiteness, spanner, triangles, ...)
  utils/     metrics, native bindings, misc types
"""

import jax as _jax

# The framework's id space is 64-bit (raw vertex ids, packed (src,dst) pair
# keys). Without x64, jnp silently truncates int64 to int32, corrupting ids
# > 2^31 and overflowing hash constants. Device compute paths stay i32/f32
# (slots, values); only id plumbing is 64-bit. TPU supports s64 scatters.
_jax.config.update("jax_enable_x64", True)

from .core.chunk import EDGE_ADDITION, EDGE_DELETION, EdgeChunk, make_chunk
from .core.io import TimeCharacteristic
from .core.stream import (
    EdgeStream,
    StreamContext,
    edge_stream_from_edges,
    edge_stream_from_file,
    edge_stream_from_source,
)
from .core.vertices import IdentityVertexTable, VertexTable

__version__ = "0.1.0"

__all__ = [
    "EDGE_ADDITION",
    "EDGE_DELETION",
    "EdgeChunk",
    "EdgeStream",
    "IdentityVertexTable",
    "StreamContext",
    "TimeCharacteristic",
    "VertexTable",
    "edge_stream_from_edges",
    "edge_stream_from_file",
    "edge_stream_from_source",
    "make_chunk",
]
