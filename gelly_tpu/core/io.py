"""Edge-list ingestion: file/array sources producing padded EdgeChunks.

Mirrors the reference examples' hand-rolled readers (whitespace/tab split with
``%`` comment lines, e.g. ``M/example/ExactTriangleCount.java:183-192`` and
``M/example/ConnectedComponentsExample.java:105-118``) plus the two time
semantics of ``SimpleEdgeStream``'s constructors
(``M/SimpleEdgeStream.java:69-90``): ingestion time (arrival order) vs event
time (an extractor over the record).

Sources are plain Python iterators of :class:`~gelly_tpu.core.chunk.EdgeChunk`;
the device pipeline consumes them chunk by chunk. A native C++ parser
(``native/edgelist_parser.cc``) accelerates the text hot path when built; the
pure-numpy fallback is always available.
"""

from __future__ import annotations

import enum
import itertools
from typing import Callable, Iterable, Iterator, Sequence

import numpy as np

from .chunk import EdgeChunk, make_chunk
from .vertices import IdentityVertexTable, VertexTable

DEFAULT_CHUNK_SIZE = 4096


class TimeCharacteristic(enum.Enum):
    """SimpleEdgeStream ctor #1 → INGESTION, ctor #2 → EVENT
    (M/SimpleEdgeStream.java:69-90)."""

    INGESTION = "ingestion"
    EVENT = "event"


def parse_edge_list_text(
    text: str,
    comment_prefixes: Sequence[str] = ("%", "#"),
    delimiter: str | None = None,
    num_value_cols: int = 0,
) -> tuple[np.ndarray, np.ndarray, np.ndarray | None]:
    """Parse an edge-list string into (src, dst, vals?) numpy arrays.

    Lines starting with any of ``comment_prefixes`` (after strip) are skipped;
    fields split on ``delimiter`` (None = any whitespace, like the reference's
    ``line.split("\\s+")`` / ``"\\t"`` variants).
    """
    srcs: list[int] = []
    dsts: list[int] = []
    vals: list[float] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or any(line.startswith(p) for p in comment_prefixes):
            continue
        fields = line.split(delimiter) if delimiter else line.split()
        try:  # malformed lines are skipped (native parser parity)
            s, d = int(fields[0]), int(fields[1])
        except (ValueError, IndexError):
            continue
        srcs.append(s)
        dsts.append(d)
        if num_value_cols:
            # Missing value column defaults to 1.0 (native parser parity).
            try:
                vals.append(float(fields[2]))
            except (ValueError, IndexError):
                vals.append(1.0)
    src = np.asarray(srcs, dtype=np.int64)
    dst = np.asarray(dsts, dtype=np.int64)
    val = np.asarray(vals, dtype=np.float64) if num_value_cols else None
    return src, dst, val


def read_edge_list(
    path: str,
    comment_prefixes: Sequence[str] = ("%", "#"),
    delimiter: str | None = None,
    num_value_cols: int = 0,
    use_native: bool = True,
) -> tuple[np.ndarray, np.ndarray, np.ndarray | None]:
    """Read a whole edge-list file into numpy arrays (host)."""
    if use_native and delimiter is None:
        try:
            from ..utils.native import parse_edge_list_file

            if num_value_cols:
                return parse_edge_list_file(path, want_vals=True)
            return (*parse_edge_list_file(path), None)
        except Exception:
            pass  # fall back to the pure-python parser
    with open(path) as f:
        return parse_edge_list_text(
            f.read(), comment_prefixes, delimiter, num_value_cols
        )


class EdgeChunkSource:
    """Iterator of EdgeChunks over host edge arrays, with densification.

    - ``time`` = INGESTION: timestamps are the global arrival index (the
      reference's IngestionTime, ctor #1).
    - ``time`` = EVENT: ``timestamps`` (or ``ts_fn(src_raw, dst_raw, val)``)
      supplies event time, assumed ascending like the reference's
      ``AscendingTimestampExtractor`` (ctor #2).

    Yielded chunks are zero-copy SLICES of the input arrays (and of one
    whole-stream dense encode): see :func:`make_chunk`'s no-mutation
    contract — callers must not mutate ``src_raw``/``dst_raw``/``val``
    after construction while chunks may still be in flight.
    """

    def __init__(
        self,
        src_raw: np.ndarray,
        dst_raw: np.ndarray,
        val: np.ndarray | None = None,
        timestamps: np.ndarray | None = None,
        events: np.ndarray | None = None,
        chunk_size: int = DEFAULT_CHUNK_SIZE,
        table: VertexTable | IdentityVertexTable | None = None,
        time: TimeCharacteristic = TimeCharacteristic.INGESTION,
        ts_fn: Callable | None = None,
        val_dtype=np.float32,
    ):
        self.src_raw = np.asarray(src_raw)
        self.dst_raw = np.asarray(dst_raw)
        self.val = None if val is None else np.asarray(val)
        self.events = None if events is None else np.asarray(events, np.int8)
        self.chunk_size = int(chunk_size)
        self.table = table if table is not None else VertexTable()
        self.time = time
        self.val_dtype = val_dtype
        n = self.src_raw.shape[0]
        if time is TimeCharacteristic.EVENT:
            if timestamps is not None:
                self.timestamps = np.asarray(timestamps, np.int64)
            elif ts_fn is not None:
                self.timestamps = np.asarray(
                    ts_fn(self.src_raw, self.dst_raw, self.val), np.int64
                )
            else:
                raise ValueError("EVENT time requires timestamps or ts_fn")
        else:
            self.timestamps = np.arange(n, dtype=np.int64)
        # Resume-seek bookkeeping: the edge index the stateful table has
        # been warmed through (every id below it is already encoded, in
        # stream order). iter_from() consults it so a resume never
        # re-encodes a prefix this source object already pushed through
        # the table — the array-source analog of the sharded readers'
        # recorded per-chunk byte offsets (O(1) seek instead of
        # O(position) re-read).
        self._encoded_upto = 0

    @property
    def num_edges(self) -> int:
        return int(self.src_raw.shape[0])

    @property
    def num_chunks(self) -> int:
        return -(-self.num_edges // self.chunk_size)

    def __iter__(self) -> Iterator[EdgeChunk]:
        return self.iter_from(0)

    def iter_from(self, chunk_index: int) -> Iterator[EdgeChunk]:
        """Chunk iterator starting at ``chunk_index`` — the resume seek used
        by the resilient driver (``engine/resilience.py``).

        A stateful :class:`VertexTable` assigns slots in first-seen stream
        order, so the skipped prefix is still ENCODED (same per-chunk
        src-then-dst order as a from-zero run) to warm the table — slot
        assignment, and hence every downstream summary, stays bit-identical
        to an uninterrupted run. Re-encoding already-known ids is idempotent,
        so restarting a partially-consumed source is safe too. Identity
        tables seek in O(1) — and so does any resume over a prefix this
        source object already encoded: the first pass records its
        encoded-through watermark, so the in-process retry/restart path
        (``restartable_prefetch``, the resilient driver) skips the warm
        loop entirely instead of paying an O(position) re-encode per
        restart.
        """
        if chunk_index < 0:
            raise ValueError(f"chunk_index must be >= 0, got {chunk_index}")
        return self._iter_impl(chunk_index)

    def _iter_impl(self, chunk_index: int) -> Iterator[EdgeChunk]:
        n = self.num_edges
        cs = self.chunk_size
        start = min(chunk_index * cs, n)
        src_all = dst_all = None
        if isinstance(self.table, IdentityVertexTable):
            # Identity densification is stateless: encode the whole stream
            # once so per-chunk src/dst are zero-copy views (the per-chunk
            # astype was a serial ~ms/chunk cost on the ingest thread).
            src_all = self.table.encode(self.src_raw)
            dst_all = self.table.encode(self.dst_raw)
        else:
            # Warm only the part of the prefix the table has NOT already
            # seen from this source (``_encoded_upto`` = recorded resume
            # position): a resume at-or-below the watermark re-encodes
            # nothing. Encoding is idempotent for known ids, so a
            # watermark that lags (first pass stopped early) just means
            # the remainder of the prefix is encoded here, exactly as a
            # from-zero run would have.
            for lo in range(min(self._encoded_upto, start), start, cs):
                hi = min(lo + cs, n)
                self.table.encode(self.src_raw[lo:hi])
                self.table.encode(self.dst_raw[lo:hi])
            if start > self._encoded_upto:
                self._encoded_upto = start
        for lo in range(start, n, cs):
            hi = min(lo + cs, n)
            if src_all is not None:
                src = src_all[lo:hi]
                dst = dst_all[lo:hi]
            else:
                src = self.table.encode(self.src_raw[lo:hi])
                dst = self.table.encode(self.dst_raw[lo:hi])
                if hi > self._encoded_upto:
                    self._encoded_upto = hi
            yield make_chunk(
                src,
                dst,
                raw_src=self.src_raw[lo:hi],
                raw_dst=self.dst_raw[lo:hi],
                val=None if self.val is None else self.val[lo:hi],
                ts=self.timestamps[lo:hi],
                event=None if self.events is None else self.events[lo:hi],
                capacity=cs,
                val_dtype=self.val_dtype,
                device=False,  # lazy H2D: host window logic stays host-side
            )


def chunks_from_file(
    path: str,
    chunk_size: int = DEFAULT_CHUNK_SIZE,
    table: VertexTable | IdentityVertexTable | None = None,
    num_value_cols: int = 0,
    time: TimeCharacteristic = TimeCharacteristic.INGESTION,
    ts_fn: Callable | None = None,
    **kw,
) -> EdgeChunkSource:
    src, dst, val = read_edge_list(path, num_value_cols=num_value_cols, **kw)
    return EdgeChunkSource(
        src, dst, val, chunk_size=chunk_size, table=table, time=time, ts_fn=ts_fn
    )


def chunks_from_edges(
    edges: Iterable[tuple],
    chunk_size: int = DEFAULT_CHUNK_SIZE,
    table: VertexTable | IdentityVertexTable | None = None,
    time: TimeCharacteristic = TimeCharacteristic.INGESTION,
    timestamps: np.ndarray | None = None,
    ts_fn: Callable | None = None,
) -> EdgeChunkSource:
    """Source from (src, dst[, val]) tuples — the tests' fixture entry point."""
    rows = list(edges)
    if not rows:
        return EdgeChunkSource(
            np.zeros(0, np.int64), np.zeros(0, np.int64),
            chunk_size=chunk_size, table=table,
        )
    src = np.asarray([r[0] for r in rows], dtype=np.int64)
    dst = np.asarray([r[1] for r in rows], dtype=np.int64)
    val = (
        np.asarray([r[2] for r in rows], dtype=np.float64)
        if len(rows[0]) > 2
        else None
    )
    return EdgeChunkSource(
        src, dst, val, chunk_size=chunk_size, table=table, time=time,
        timestamps=timestamps, ts_fn=ts_fn,
    )
