"""EdgeStream — the TPU-native ``GraphStream`` / ``SimpleEdgeStream``.

Mirrors the public surface of the reference's abstract ``GraphStream``
(``M/GraphStream.java:38-141``) and its only concrete implementation
``SimpleEdgeStream`` (``M/SimpleEdgeStream.java:55-577``): edge/vertex property
streams, transforms (map/filter/distinct/reverse/undirected/union), degree and
count streams, windowed slices, and the ``aggregate`` plugin boundary.

Execution model: a stream is a lazy pipeline of pure, jitted
``EdgeChunk -> EdgeChunk`` transforms over a host-side chunk source. Stateful
operators (distinct, degrees, counters) thread fixed-shape device state through
a jitted ``step(state, chunk) -> (state, emission)`` — the functional analog of
Flink's keyed operator state, with no shared mutable state to race on.

Emission contract: the reference emits one record per input edge
("continuously improving" streams, e.g. ``DegreeMapFunction`` re-emits the
updated degree per edge, ``M/SimpleEdgeStream.java:461-478``). Here emissions
are **chunk-grained**: one update batch per processed chunk, containing the
latest value for every key touched by that chunk. Final values are identical;
only the intermediate granularity differs (documented deviation).
"""

from __future__ import annotations

import dataclasses
import itertools
from functools import partial
from typing import Callable, Iterable, Iterator, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import segments
from ..ops.hashset import DeviceHashSet
from .chunk import EdgeChunk, concat_chunks
from .io import EdgeChunkSource, TimeCharacteristic, chunks_from_edges, chunks_from_file
from .vertices import IdentityVertexTable, VertexTable


@dataclasses.dataclass
class StreamContext:
    """Shared per-pipeline context: vertex table + static slot capacity.

    ``vertex_capacity`` bounds the dense slot space all summary arrays are
    sized to. It is a static compile-time constant (XLA needs fixed shapes);
    pick it ≥ the number of distinct vertices the stream will see.
    """

    table: VertexTable | IdentityVertexTable
    vertex_capacity: int

    def decode(self, slots) -> np.ndarray:
        return self.table.decode(np.asarray(slots))


class Update(NamedTuple):
    """A chunk-grained emission: latest ``values`` for the touched ``slots``."""

    slots: jax.Array  # i32[k] dense vertex slots (may contain duplicates' last)
    values: jax.Array
    valid: jax.Array  # bool[k]

    def to_pairs(self, ctx: StreamContext) -> list[tuple[int, object]]:
        m = np.asarray(self.valid).astype(bool)
        ids = ctx.decode(np.asarray(self.slots)[m])
        vals = np.asarray(self.values)[m]
        return list(zip(ids.tolist(), vals.tolist()))


# ---------------------------------------------------------------------- #
# module-level jitted steps (jax.jit caches by function identity: defining
# these inside the iterator methods would recompile on every drain)


@jax.jit
def _vertices_step(seen, c: EdgeChunk):
    n = seen.shape[0]
    ids = jnp.concatenate([c.src, c.dst])
    raw = jnp.concatenate([c.raw_src, c.raw_dst])
    ok = jnp.concatenate([c.valid, c.valid])
    first_in_chunk = segments.first_occurrence_mask(ids, ok, n)
    new = first_in_chunk & ~seen[ids]
    seen2 = segments.mark_seen(seen, ids, ok)
    return seen2, Update(ids, raw, new)


@jax.jit
def _edge_count_step(total, c: EdgeChunk):
    delta = jnp.where(c.event == 1, -1, 1)
    return total + jnp.sum(jnp.where(c.valid, delta, 0))


@jax.jit
def _vertex_count_step(seen, c: EdgeChunk):
    ids = jnp.concatenate([c.src, c.dst])
    ok = jnp.concatenate([c.valid, c.valid])
    seen2 = segments.mark_seen(seen, ids, ok)
    return seen2, jnp.sum(seen2.astype(jnp.int64))


@partial(jax.jit, static_argnames=("cap",))
def _pair_keys(c: EdgeChunk, cap: int):
    return c.src.astype(jnp.int64) * jnp.int64(cap) + c.dst.astype(jnp.int64)


@partial(jax.jit, static_argnames=("count_out", "count_in"))
def _degree_step(deg, c: EdgeChunk, count_out: bool, count_in: bool):
    n = deg.shape[0]
    delta = jnp.where(c.event == 1, -1, 1).astype(jnp.int64)
    if count_out:
        deg = segments.masked_scatter_add(deg, c.src, delta, c.valid)
    if count_in:
        deg = segments.masked_scatter_add(deg, c.dst, delta, c.valid)
    ids = jnp.concatenate([c.src, c.dst])
    ok = jnp.concatenate([c.valid & count_out, c.valid & count_in])
    touched = segments.first_occurrence_mask(ids, ok, n)
    return deg, Update(ids, deg[ids], touched)


class EdgeStream:
    """A (possibly transformed) stream of edge chunks.

    Construct with :func:`edge_stream_from_edges` / ``from_file`` or by
    transforming an existing stream. Iterating yields :class:`EdgeChunk`s.
    """

    def __init__(self, chunks_fn: Callable[[], Iterator[EdgeChunk]],
                 ctx: StreamContext, source=None):
        self._chunks_fn = chunks_fn
        self.ctx = ctx
        # The underlying seekable EdgeChunkSource when this stream reads one
        # directly (None for transformed/derived streams): chunks_from then
        # fast-forwards in O(1) instead of re-iterating the prefix.
        self.source = source

    # ------------------------------------------------------------------ #
    # plumbing

    def __iter__(self) -> Iterator[EdgeChunk]:
        return self._chunks_fn()

    def get_edges(self) -> Iterator[EdgeChunk]:
        """The stream of edge chunks (GraphStream.getEdges)."""
        return iter(self)

    def chunks_from(self, position: int) -> Iterator[EdgeChunk]:
        """Chunk iterator starting at chunk index ``position`` — the resume
        fast-forward used by ``engine/resilience.py``. Seeks through the
        underlying source when it supports ``iter_from``; otherwise skips
        the prefix by iteration (always correct, O(position) on resume)."""
        if position <= 0:
            return self._chunks_fn()
        if self.source is not None and hasattr(self.source, "iter_from"):
            return self.source.iter_from(position)
        return itertools.islice(self._chunks_fn(), position, None)

    def _mapped(self, fn: Callable[[EdgeChunk], EdgeChunk]) -> "EdgeStream":
        jfn = jax.jit(fn)
        src = self._chunks_fn

        def gen():
            for c in src():
                yield jfn(c)

        return EdgeStream(gen, self.ctx)

    def collect_edges(self, raw: bool = True) -> list[tuple]:
        """Drain the stream into a host list of (src, dst, val) tuples."""
        out: list[tuple] = []
        for c in self:
            s, d, v = c.compact_edges(raw=raw)
            out.extend(zip(s.tolist(), d.tolist(), v.tolist()))
        return out

    # ------------------------------------------------------------------ #
    # stateless transforms (GraphStream.mapEdges / filterEdges / ...)

    def map_edges(self, fn) -> "EdgeStream":
        """Vectorized edge-value map: ``fn(raw_src, raw_dst, val) -> new_val``
        (GraphStream.mapEdges, M/SimpleEdgeStream.java:217-222)."""
        return self._mapped(
            lambda c: c._replace(val=fn(c.raw_src, c.raw_dst, c.val))
        )

    def filter_edges(self, pred) -> "EdgeStream":
        """Keep edges where ``pred(raw_src, raw_dst, val)`` is True
        (M/SimpleEdgeStream.java:290-293). Filtering only flips the valid
        mask — no data movement."""
        return self._mapped(lambda c: c.mask(pred(c.raw_src, c.raw_dst, c.val)))

    def filter_vertices(self, pred) -> "EdgeStream":
        """Keep an edge iff **both** endpoints pass ``pred(raw_id)`` —
        the reference's ApplyVertexFilterToEdges semantics
        (M/SimpleEdgeStream.java:264-281)."""
        return self._mapped(
            lambda c: c.mask(pred(c.raw_src) & pred(c.raw_dst))
        )

    def reverse(self) -> "EdgeStream":
        return self._mapped(lambda c: c.reverse())

    def undirected(self) -> "EdgeStream":
        return self._mapped(lambda c: c.undirected())

    def union(self, other: "EdgeStream") -> "EdgeStream":
        """Merge two streams over the same context
        (M/SimpleEdgeStream.java:343-345). Chunks interleave round-robin."""
        if other.ctx is not self.ctx:
            raise ValueError("union requires streams sharing a StreamContext")
        a_fn, b_fn = self._chunks_fn, other._chunks_fn

        def gen():
            a, b = a_fn(), b_fn()
            while True:
                stop_a = stop_b = False
                try:
                    yield next(a)
                except StopIteration:
                    stop_a = True
                try:
                    yield next(b)
                except StopIteration:
                    stop_b = True
                if stop_a and stop_b:
                    return

        return EdgeStream(gen, self.ctx)

    def distinct(self, device: bool | None = None) -> "EdgeStream":
        """Drop duplicate (src, dst) pairs, exact first-wins streaming
        semantics (DistinctEdgeMapper, M/SimpleEdgeStream.java:301-323).

        The strategy follows the first chunk's residency (``device=None``):
        host-resident streams get a vectorized host dedup — ``np.unique``
        marks the first in-chunk occurrence and LSM-style sorted key runs
        (geometrically merged, so no O(|seen|) copy per chunk) drop keys
        from prior chunks; ~50x the per-edge device scan's rate. Device-
        resident pipelines (or ``device=True``) keep the dedup state in
        HBM (``DeviceHashSet``) and never sync to the host.
        """
        src_fn = self._chunks_fn
        cap = self.ctx.vertex_capacity

        def dedup_device(chunks):
            hset = DeviceHashSet()
            for c in chunks:
                is_new = hset.insert(_pair_keys(c, cap), c.valid)
                yield c.mask(is_new)

        def dedup_host(chunks):
            runs: list[np.ndarray] = []  # disjoint sorted key runs
            for c in chunks:
                h = c.to_numpy()
                keys = h.src.astype(np.int64) * np.int64(cap) + h.dst
                v_idx = np.nonzero(h.valid)[0]
                k = keys[v_idx]
                _, first = np.unique(k, return_index=True)
                new_sub = np.zeros(k.shape, bool)
                new_sub[first] = True
                for run in runs:  # probe only still-new candidates
                    cand = np.nonzero(new_sub)[0]
                    if not cand.size:
                        break
                    q = k[cand]
                    pos = np.minimum(
                        np.searchsorted(run, q), run.size - 1
                    )
                    new_sub[cand[run[pos] == q]] = False
                fresh = np.sort(k[new_sub])
                if fresh.size:
                    runs.append(fresh)
                    # geometric merging bounds the run count (and thus
                    # probes per chunk) at O(log |seen|).
                    while (len(runs) >= 2
                           and runs[-2].size <= 2 * runs[-1].size):
                        b, a = runs.pop(), runs.pop()
                        runs.append(np.sort(np.concatenate([a, b])))
                is_new = np.zeros(keys.shape, bool)
                is_new[v_idx[new_sub]] = True
                yield h.mask(is_new) if c.is_host() else c.mask(
                    jnp.asarray(is_new)
                )

        def gen():
            it = iter(src_fn())
            c0 = next(it, None)
            if c0 is None:
                return
            chunks = itertools.chain([c0], it)
            use_device = device if device is not None else not c0.is_host()
            yield from (
                dedup_device(chunks) if use_device else dedup_host(chunks)
            )

        return EdgeStream(gen, self.ctx)

    # ------------------------------------------------------------------ #
    # vertex / property streams

    def get_vertices(self) -> Iterator[Update]:
        """Stream of first-seen vertices (GraphStream.getVertices,
        M/SimpleEdgeStream.java:116-121): per chunk, an Update whose slots are
        the vertices never seen before."""
        n = self.ctx.vertex_capacity

        def gen():
            seen = jnp.zeros((n,), bool)
            for c in self._chunks_fn():
                seen, upd = _vertices_step(seen, c)
                yield upd

        return gen()

    def _degrees(self, count_out: bool, count_in: bool) -> "DegreeStream":
        return DegreeStream(self, count_out=count_out, count_in=count_in)

    def get_degrees(self) -> "DegreeStream":
        """Continuous (vertex, degree) stream counting both directions
        (M/SimpleEdgeStream.java:413-416: DegreeTypeSeparator(true, true))."""
        return self._degrees(count_out=True, count_in=True)

    def get_out_degrees(self) -> "DegreeStream":
        return self._degrees(count_out=True, count_in=False)

    def get_in_degrees(self) -> "DegreeStream":
        return self._degrees(count_out=False, count_in=True)

    def number_of_edges(self) -> Iterator[int]:
        """Running total edge count, one value per chunk
        (TotalEdgeCountMapper, M/SimpleEdgeStream.java:392-404). Deletion
        events count -1 so the total tracks the live graph, consistent with
        DegreeStream."""

        def gen():
            total = jnp.zeros((), jnp.int64)
            for c in self._chunks_fn():
                total = _edge_count_step(total, c)
                yield int(total)

        return gen()

    def number_of_vertices(self) -> Iterator[int]:
        """Running distinct-vertex count, emitted on change
        (globalAggregate + emit-on-change, M/SimpleEdgeStream.java:366-383,
        562-576)."""

        n = self.ctx.vertex_capacity

        def gen():
            seen = jnp.zeros((n,), bool)
            last = -1
            for c in self._chunks_fn():
                seen, count = _vertex_count_step(seen, c)
                count = int(count)
                if count != last:  # emit-on-change dedup (GlobalAggregateMapper)
                    last = count
                    yield count

        return gen()

    def global_aggregate(self, update_fn, initial_state, emit_on_change: bool = True):
        """Generic centralized aggregate (M/SimpleEdgeStream.java:505-519):
        ``update_fn(state, chunk) -> (state, emission)`` runs jitted per chunk;
        emission is yielded (deduped on change when hashable)."""
        jfn = jax.jit(update_fn)

        def gen():
            state = initial_state
            last = object()
            for c in self._chunks_fn():
                state, em = jfn(state, c)
                host = jax.tree.map(np.asarray, em)
                if emit_on_change:
                    key = jax.tree.map(lambda a: a.tobytes(), host)
                    if key == last:
                        continue
                    last = key
                yield host

        return gen()

    # ------------------------------------------------------------------ #
    # plugin boundaries (implemented in engine / snapshot modules)

    def aggregate(self, aggregation, **runner_kw):
        """Run a SummaryAggregation over this stream
        (GraphStream.aggregate, M/GraphStream.java:139-140). Returns a
        SummaryStream; see gelly_tpu.engine.aggregation."""
        from ..engine.aggregation import run_aggregation

        return run_aggregation(aggregation, self, **runner_kw)

    def slice(self, window_ms: int, direction: str = "out",
              window_capacity: int | None = None,
              allowed_lateness: int = 0) -> "SnapshotStream":
        """Discretize into per-vertex tumbling-window neighborhoods
        (M/SimpleEdgeStream.java:135-167). direction ∈ {out, in, all}.
        ``allowed_lateness`` (ms) buffers out-of-order edges up to that
        bound (core/windows.py watermark semantics)."""
        from .snapshot import SnapshotStream

        return SnapshotStream(self, window_ms, direction, window_capacity,
                              allowed_lateness)

    def build_neighborhood(self, directed: bool = False,
                           capacity: int | None = None,
                           max_degree: int | None = None):
        """Stream of growing adjacency snapshots
        (BuildNeighborhoods, M/SimpleEdgeStream.java:531-560). ``capacity``
        caps the N×N adjacency below the stream's vertex space (the exact
        path's memory bound); ``max_degree`` switches to the capped-degree
        sparse table (O(N*D) memory, the N >= 1M path); see
        gelly_tpu.core.neighborhood."""
        from .neighborhood import NeighborhoodStream

        return NeighborhoodStream(self, directed, capacity, max_degree)


class DegreeStream:
    """Continuous degree stream (the reference's getDegrees family).

    Iterating yields one :class:`Update` per chunk with the new degrees of all
    vertices touched by that chunk. Honors EDGE_DELETION events with -1
    contributions (used by the DegreeDistribution example,
    M/example/DegreeDistribution.java:70-111).
    """

    def __init__(self, stream: EdgeStream, count_out: bool, count_in: bool):
        self.stream = stream
        self.count_out = count_out
        self.count_in = count_in

    def __iter__(self) -> Iterator[Update]:
        n = self.stream.ctx.vertex_capacity
        deg = jnp.zeros((n,), jnp.int64)
        for c in self.stream:
            deg, upd = _degree_step(deg, c, self.count_out, self.count_in)
            yield upd

    def final_degrees(self) -> dict[int, int]:
        """Drain the stream; return {raw_vertex_id: degree}."""
        ctx = self.stream.ctx
        result: dict[int, int] = {}
        for upd in self:
            for k, v in upd.to_pairs(ctx):
                result[k] = int(v)
        return result


# ---------------------------------------------------------------------- #
# constructors


def edge_stream_from_source(source: EdgeChunkSource,
                            vertex_capacity: int) -> EdgeStream:
    table = source.table
    # Bind the table's capacity to the summary-array slot space so overflow
    # raises at ingest instead of silently dropping/aliasing scatter updates.
    if getattr(table, "capacity", None) is None:
        table.capacity = vertex_capacity
    elif table.capacity > vertex_capacity:
        raise ValueError(
            f"table capacity {table.capacity} exceeds vertex_capacity "
            f"{vertex_capacity}"
        )
    ctx = StreamContext(table=table, vertex_capacity=vertex_capacity)
    return EdgeStream(lambda: iter(source), ctx, source=source)


def edge_stream_from_edges(
    edges: Iterable[tuple],
    vertex_capacity: int = 1 << 12,
    chunk_size: int = 256,
    time: TimeCharacteristic = TimeCharacteristic.INGESTION,
    timestamps=None,
    ts_fn=None,
    table=None,
) -> EdgeStream:
    src = chunks_from_edges(
        edges, chunk_size=chunk_size, table=table, time=time,
        timestamps=timestamps, ts_fn=ts_fn,
    )
    return edge_stream_from_source(src, vertex_capacity)


def edge_stream_from_file(
    path: str,
    vertex_capacity: int = 1 << 20,
    chunk_size: int = 4096,
    **kw,
) -> EdgeStream:
    src = chunks_from_file(path, chunk_size=chunk_size, **kw)
    return edge_stream_from_source(src, vertex_capacity)
