"""SnapshotStream — per-vertex tumbling-window neighborhood aggregation.

TPU-native re-design of the reference's ``SnapshotStream``
(``M/SnapshotStream.java:46-182``) produced by ``SimpleEdgeStream.slice``
(``M/SimpleEdgeStream.java:135-167``): edges are grouped by a *group vertex*
(the edge source after direction normalization) into tumbling event/ingestion
time windows, and each vertex's window neighborhood is aggregated with one of

- :meth:`SnapshotStream.fold_neighbors`  — ``foldEdges(acc, v, nbr, val)``
  sequential fold per vertex (``M/SnapshotStream.java:61-86``),
- :meth:`SnapshotStream.reduce_on_edges` — associative reduce over edge
  values per vertex (``:100-120``),
- :meth:`SnapshotStream.apply_on_neighbors` — a UDF over the whole
  neighborhood (``:129-181``).

Direction handling mirrors ``slice`` exactly: ``out`` keys edges by source;
``in`` routes through ``reverse()`` (``M/SimpleEdgeStream.java:153-155``);
``all`` routes through ``undirected()`` so each edge lands in both endpoints'
windows (``:159-163``).

Execution model: instead of Flink's keyed window operator (hash shuffle +
per-key state), a window is a fixed-capacity device **edge buffer**. Chunks
are masked per window and appended compacted; at window close the buffer is
sorted by group vertex once, and every aggregation runs as segment ops over
the sorted runs:

- ``reduce_on_edges`` → segmented ``associative_scan`` (O(log W) depth —
  the reference requires the reduce to be associative too, so parity holds);
- ``fold_neighbors``  → segmented sequential ``lax.scan`` (exact per-edge
  fold-order parity; O(W) depth — prefer reduce/apply for throughput);
- ``apply_on_neighbors`` → the vectorized :class:`NeighborhoodView` contract
  (sorted COO + segment metadata), the TPU-native shape of the reference's
  per-vertex ``Iterable`` UDF. A host-side per-vertex iterator adapter is
  provided for parity-style UDFs.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import segments
from .chunk import EdgeChunk


class WindowUpdate(NamedTuple):
    """One closed window's per-vertex results.

    ``slots``/``values`` are aligned arrays; only positions with ``valid``
    carry a (group-vertex, result) pair.
    """

    window: int
    slots: jax.Array
    values: Any
    valid: jax.Array

    def to_pairs(self, ctx) -> list[tuple[int, Any]]:
        m = np.asarray(self.valid).astype(bool)
        ids = ctx.decode(np.asarray(self.slots)[m])
        vals = jax.tree.map(lambda a: np.asarray(a)[m], self.values)
        if isinstance(vals, np.ndarray):
            return list(zip(ids.tolist(), vals.tolist()))
        leaves = jax.tree.leaves(vals)
        return list(zip(ids.tolist(), zip(*(l.tolist() for l in leaves))))


class NeighborhoodView(NamedTuple):
    """Sorted per-window COO with segment metadata — the vectorized
    neighborhood contract handed to ``apply_on_neighbors`` UDFs.

    All arrays have length W (the window buffer capacity):

    - ``key``: i32[W] group-vertex slots, ascending (padding keys sort last);
    - ``nbr``: i32[W] neighbor slots;
    - ``val``: EV[W] edge values;
    - ``valid``: bool[W];
    - ``starts``: bool[W] — True at the first edge of each vertex's run;
    - ``seg_id``: i32[W] — dense index of the run each edge belongs to.
    """

    key: jax.Array
    nbr: jax.Array
    val: jax.Array
    valid: jax.Array
    starts: jax.Array
    seg_id: jax.Array

    def ends(self) -> jax.Array:
        """True at the last edge of each vertex's run."""
        nxt = jnp.concatenate([self.starts[1:], jnp.ones((1,), bool)])
        nxt_invalid = jnp.concatenate([~self.valid[1:], jnp.ones((1,), bool)])
        return self.valid & (nxt | nxt_invalid)

    def per_vertex(self, ctx) -> Iterator[tuple[int, list[tuple[int, Any]]]]:
        """Host adapter: yields (raw_vertex_id, [(raw_neighbor, val), ...]) —
        the reference's ``Iterable<Tuple2<K, EV>>`` shape
        (M/SnapshotStream.java:143-174). Slow path; for tests/parity."""
        key = np.asarray(self.key)
        nbr = np.asarray(self.nbr)
        val = np.asarray(self.val)
        ok = np.asarray(self.valid).astype(bool)
        groups: dict[int, list] = {}
        for k, n, v in zip(key[ok], nbr[ok], val[ok]):
            groups.setdefault(int(k), []).append((n, v))
        for k in sorted(groups):
            nbrs = groups[k]
            raw_k = int(ctx.decode(np.array([k]))[0])
            raw_n = ctx.decode(np.array([n for n, _ in nbrs]))
            yield raw_k, list(zip(raw_n.tolist(), [v for _, v in nbrs]))


# ------------------------------------------------------------------ #
# jitted window-buffer plumbing (module-level for jit cache reuse)


def _assemble_buffer(parts, capacity: int, val_dtype, val_shape=(),
                     sort: bool = True):
    """Host-side window assembly: compact each chunk's valid entries with
    numpy boolean indexing, pack into one padded buffer, and key-sort on
    the host. One H2D per window instead of per-chunk device scatters plus
    a device bitonic sort — numpy's radix argsort on ≤100k keys is ~20x
    faster than the TPU sort at these sizes, and the sorted buffer uploads
    once. ``sort=False`` skips the key sort for consumers whose kernels
    are order-independent (the packed triangle count)."""
    bk = np.full((capacity,), segments.INT_MAX, np.int32)  # padding sorts last
    bn = np.zeros((capacity,), np.int32)
    bv = np.zeros((capacity,) + val_shape, np.dtype(val_dtype))
    bo = np.zeros((capacity,), bool)
    fill = 0
    for c in parts:
        m = np.asarray(c.valid)
        k = np.asarray(c.src)[m]
        fill2 = fill + k.shape[0]
        bk[fill:fill2] = k
        bn[fill:fill2] = np.asarray(c.dst)[m]
        bv[fill:fill2] = np.asarray(c.val)[m]
        bo[fill:fill2] = True
        fill = fill2
    if sort:
        order = np.argsort(bk[:fill], kind="stable")
        bk[:fill] = bk[:fill][order]
        bn[:fill] = bn[:fill][order]
        bv[:fill] = bv[:fill][order]
    return bk, bn, bv, bo


@jax.jit
def _sorted_view(buf) -> NeighborhoodView:
    # Input is already key-sorted with padding keys = INT_MAX (host
    # assembly); only the segment metadata is computed on device.
    sk, snbr, sval, so = (jnp.asarray(x) for x in buf)
    starts = segments.segment_starts(sk, so)
    seg_id = jnp.cumsum(starts.astype(jnp.int32)) - 1
    return NeighborhoodView(sk, snbr, sval, so, starts, seg_id)


class SnapshotStream:
    """The graph-window stream: iterate one of the aggregation methods.

    ``window_capacity`` bounds edges per window per stream (static shape);
    overflow raises rather than silently dropping.
    """

    def __init__(self, stream, window_ms: int, direction: str = "out",
                 window_capacity: int | None = None,
                 allowed_lateness: int = 0):
        if direction not in ("out", "in", "all"):
            raise ValueError(f"direction must be out/in/all, got {direction}")
        self.stream = stream
        self.window_ms = int(window_ms)
        self.direction = direction
        self.window_capacity = window_capacity
        self.allowed_lateness = int(allowed_lateness)
        self.stats = {"late_edges": 0, "windows_closed": 0}

    # -------------------------------------------------------------- #

    def _transformed(self) -> Iterator[EdgeChunk]:
        # Direction normalization per slice() (M/SimpleEdgeStream.java:149-163).
        for c in self.stream:
            if self.direction == "in":
                yield c.reverse()
            elif self.direction == "all":
                yield c.undirected()
            else:
                yield c

    def host_buffers(self, sort: bool = True) -> Iterator[tuple[int, tuple]]:
        """(window, (key, nbr, val, valid)) per closed window with HOST
        numpy arrays — sorted by key (unless ``sort=False``), padding keys
        = INT_MAX. The escape hatch for consumers bringing their own wire
        codec (e.g. the packed window-triangle path): nothing is uploaded
        here."""
        from .windows import tumbling_window_events

        self.stats["late_edges"] = 0
        self.stats["windows_closed"] = 0
        parts: list = []
        fill_host = 0
        cap = self.window_capacity
        for kind, w, chunk, n_valid in tumbling_window_events(
            self._transformed(), self.window_ms, self.stats,
            allowed_lateness=self.allowed_lateness,
        ):
            if kind == "close":
                c0 = parts[0]
                yield w, _assemble_buffer(
                    parts, cap, c0.val.dtype, c0.val.shape[1:], sort=sort
                )
                self.stats["windows_closed"] += 1
                parts = []
                fill_host = 0
                continue
            if cap is None:
                cap = max(4 * chunk.capacity, 1024)
            if fill_host + n_valid > cap:
                raise ValueError(
                    f"window buffer overflow (> {cap} edges in one "
                    f"window); raise window_capacity"
                )
            parts.append(chunk)
            fill_host += n_valid

    def _windows(self) -> Iterator[tuple[int, NeighborhoodView]]:
        """Assemble per-window sorted views (tumbling, ascending-ts).
        ``stats`` reflects the most recent drain (reset per run)."""
        for w, buf in self.host_buffers():
            yield w, _sorted_view(buf)

    # -------------------------------------------------------------- #
    # aggregations

    def reduce_on_edges(self, reduce_fn: Callable) -> Iterator[WindowUpdate]:
        """Per-vertex associative reduce of edge values per window
        (SnapshotStream.reduceOnEdges, M/SnapshotStream.java:100-120).

        ``reduce_fn(a, b)`` must be associative (the reference applies it in
        arbitrary combine order too). Runs as a segmented associative_scan.
        """

        @jax.jit
        def close(view: NeighborhoodView):
            def comb(a, b):
                a_start, a_val = a
                b_start, b_val = b
                val = jnp.where(b_start, b_val, reduce_fn(a_val, b_val))
                return (a_start | b_start, val)

            _, scanned = jax.lax.associative_scan(
                comb, (view.starts, view.val)
            )
            ends = view.ends()
            return WindowUpdate(-1, view.key, scanned, ends)

        def gen():
            for w, view in self._windows():
                upd = close(view)
                yield upd._replace(window=w)

        return gen()

    def fold_neighbors(self, initial_value, fold_fn: Callable,
                       ) -> Iterator[WindowUpdate]:
        """Per-vertex sequential fold ``fold_fn(acc, v, nbr, val)`` per window
        (SnapshotStream.foldNeighbors, M/SnapshotStream.java:61-86). Exact
        fold-order parity via a segmented lax.scan over the sorted buffer.
        ``initial_value`` may be any pytree (the reference folds into TupleN
        accumulators, e.g. TestSlice's Tuple2 SumEdgeValues)."""
        init = jax.tree.map(jnp.asarray, initial_value)

        @jax.jit
        def close(view: NeighborhoodView):
            def step(acc, inp):
                key, nbr, val, ok, start = inp
                acc = jax.tree.map(
                    lambda i, a: jnp.where(start, i, a), init, acc
                )
                new = fold_fn(acc, key, nbr, val)
                acc = jax.tree.map(
                    lambda n, o: jnp.where(ok, n, o), new, acc
                )
                return acc, acc

            _, accs = jax.lax.scan(
                step, init,
                (view.key, view.nbr, view.val, view.valid, view.starts),
            )
            return WindowUpdate(-1, view.key, accs, view.ends())

        def gen():
            for w, view in self._windows():
                yield close(view)._replace(window=w)

        return gen()

    def apply_on_neighbors(self, apply_fn: Callable) -> Iterator:
        """Whole-neighborhood UDF per window
        (SnapshotStream.applyOnNeighbors, M/SnapshotStream.java:129-181).

        ``apply_fn(view: NeighborhoodView)`` runs jitted once per window and
        may return any pytree (e.g. a WindowUpdate or candidate arrays). For
        reference-style per-vertex UDFs, iterate ``view.per_vertex(ctx)``
        host-side instead (slow path).
        """
        jfn = jax.jit(apply_fn)

        def gen():
            for w, view in self._windows():
                yield w, jfn(view)

        return gen()

    def views(self) -> Iterator[tuple[int, NeighborhoodView]]:
        """Raw (window, sorted view) stream — escape hatch for host UDFs."""
        return self._windows()
