"""Shared tumbling-window event iterator.

One implementation of the reference's tumbling time-window semantics
(``timeWindow(timeMillis)`` / ``slice``; ascending-timestamp contract with
allowedLateness=0) consumed by both the aggregation engine's ``window_ms``
path and the SnapshotStream buffer — a single place for window-boundary and
late-edge policy.

Yields events in stream order:

- ``("edges", window, masked_chunk, n_valid)`` — a chunk masked down to the
  edges of ``window`` (n_valid = host count of live edges in the mask);
- ``("close", window, None, 0)`` — emitted when a later window's first edge
  arrives (windows with no data never fire, Flink semantics) and once at
  end-of-stream for the final partial window.

Late edges (timestamp before the currently open window) are dropped and
counted in ``stats["late_edges"]``.

``allowed_lateness`` (ms) enables a bounded reorder buffer: window ``w``
closes only once the watermark ``max_ts_seen - allowed_lateness`` passes
its end, so edges shuffled within the lateness bound land in their correct
window (the reference's ascending-timestamp contract,
``M/SimpleEdgeStream.java:86-90``, makes lateness impossible; this is the
relaxation for out-of-order sources). Edges later than the bound are still
dropped + counted. With lateness on, a window's edges are emitted in
*arrival* order just before its close — identical final window contents to
the sorted stream, but order-sensitive per-window folds observe arrival
order, not timestamp order.
"""

from __future__ import annotations

from typing import Iterable, Iterator

import jax.numpy as jnp
import numpy as np

from .chunk import EdgeChunk


def tumbling_window_events(
    chunks: Iterable[EdgeChunk], window_ms: int, stats: dict | None = None,
    initial_window: int | None = None, allowed_lateness: int = 0,
    state_handle: dict | None = None,
    initial_state: dict | None = None,
) -> Iterator[tuple]:
    """``initial_window`` seeds the open window (checkpoint resume: edges of
    earlier, already-emitted windows count as late instead of re-opening).

    With lateness, ``state_handle`` (a caller-provided dict) gains an
    ``"export"`` callable returning the live reorder-buffer state —
    ``(wins list, [compact EdgeChunk per open window], closed_upto,
    max_ts)`` — for checkpointing, and ``initial_state`` (a prior export,
    re-shaped by the engine) seeds the buffer on resume so in-flight late
    edges survive a restart.
    """
    if allowed_lateness:
        yield from _tumbling_with_lateness(
            chunks, window_ms, stats if stats is not None else {},
            initial_window, allowed_lateness, state_handle, initial_state,
        )
        return
    if stats is None:
        stats = {}
    stats.setdefault("late_edges", 0)
    current = initial_window
    dirty = False
    for c in chunks:
        ts = np.asarray(c.ts)
        ok = np.asarray(c.valid)
        if not ok.any():
            continue
        tw = ts // window_ms
        if current is not None:
            n_late = int((ok & (tw < current)).sum())
            if n_late:
                stats["late_edges"] += n_late
                ok = ok & (tw >= current)
        for w in np.unique(tw[ok]).tolist():
            if current is None:
                current = w
            if w > current:
                if dirty:
                    yield ("close", current, None, 0)
                    dirty = False
                current = w
            mask = ok & (tw == w)
            # Host chunks stay host (an np mask keeps valid numpy); device
            # chunks get a device mask to avoid an implicit H2D per op.
            m = mask if c.is_host() else jnp.asarray(mask)
            yield ("edges", w, c.mask(m), int(mask.sum()))
            dirty = True
    if dirty:
        yield ("close", current, None, 0)


def _tumbling_with_lateness(
    chunks: Iterable[EdgeChunk], window_ms: int, stats: dict,
    initial_window: int | None, lateness: int,
    state_handle: dict | None = None,
    initial_state: dict | None = None,
) -> Iterator[tuple]:
    """Watermark-gated reorder buffer (see module docstring).

    ``pending`` holds (chunk, index-array) pairs per open window — chunks
    are immutable by contract (:func:`~gelly_tpu.core.chunk.make_chunk`),
    so buffering references is safe, and masks are compacted to indices so
    buffer memory is ∝ actually-buffered edges, not chunk capacity ×
    overlaps. Windows flush in ascending order once the watermark passes
    their end; all of a window's edge events are emitted (arrival order)
    immediately before its close event, so consumers see the same monotone
    window sequence as the zero-lateness iterator.

    Buffer bound: at most ``ceil((allowed_lateness + chunk_ts_span) /
    window_ms) + 1`` windows are open at once — the watermark trails
    max_ts by exactly the lateness, plus whatever window range a single
    chunk's own timestamps span before the post-chunk flush — each holding
    references to the chunks that touched it; worst-case host memory ∝
    that window count × chunk size. The live
    footprint is observable via ``stats["buffered_edges"]`` /
    ``stats["open_windows"]``, updated as edges enter and leave the
    buffer.
    """
    stats.setdefault("late_edges", 0)
    stats["buffered_edges"] = 0
    stats["open_windows"] = 0
    pending: dict[int, list] = {}
    # Windows below this are closed: their edges are late (drop + count).
    closed_upto = initial_window if initial_window is not None else None
    max_ts = None
    if initial_state is not None:
        # Resume: re-seed the reorder buffer from a checkpoint export —
        # one compact chunk per open window, every row live.
        closed_upto = initial_state.get("closed_upto", closed_upto)
        max_ts = initial_state.get("max_ts", max_ts)
        for w, ch in zip(initial_state["wins"], initial_state["chunks"]):
            idx = np.arange(ch.capacity, dtype=np.int32)
            pending[int(w)] = [(ch, idx)]
            stats["buffered_edges"] += ch.capacity
        stats["open_windows"] = len(pending)

    def export_state():
        wins = sorted(pending)
        out_chunks = []
        for w in wins:
            parts = pending[w]
            out_chunks.append(EdgeChunk(*(
                np.concatenate([
                    np.asarray(getattr(ch, name))[idx]
                    for ch, idx in parts
                ])
                for name in EdgeChunk._fields
            )))
        return {
            "wins": wins, "chunks": out_chunks,
            "closed_upto": closed_upto, "max_ts": max_ts,
        }

    if state_handle is not None:
        state_handle["export"] = export_state

    def flush(upto):
        for w in sorted(w for w in pending if upto is None or w < upto):
            for ch, idx in pending.pop(w):
                m = np.zeros(ch.capacity, bool)
                m[idx] = True
                mm = m if ch.is_host() else jnp.asarray(m)
                stats["buffered_edges"] -= idx.shape[0]
                yield ("edges", w, ch.mask(mm), idx.shape[0])
            stats["open_windows"] = len(pending)
            yield ("close", w, None, 0)

    for c in chunks:
        ts = np.asarray(c.ts)
        ok = np.asarray(c.valid)
        if not ok.any():
            continue
        tw = ts // window_ms
        # Lateness is judged against the watermark as it stood BEFORE this
        # chunk: an edge is late only if its window already closed. (Using
        # this chunk's own max_ts first would make a chunk spanning more
        # than the lateness bound drop its own earlier edges — even on a
        # perfectly sorted stream.)
        if closed_upto is not None:
            n_late = int((ok & (tw < closed_upto)).sum())
            if n_late:
                stats["late_edges"] += n_late
                ok = ok & (tw >= closed_upto)
            if not ok.any():
                continue
        for w in np.unique(tw[ok]).tolist():
            idx = np.nonzero(ok & (tw == w))[0].astype(np.int32)
            pending.setdefault(w, []).append((c, idx))
            stats["buffered_edges"] += idx.shape[0]
        stats["open_windows"] = len(pending)
        # Now advance the watermark and flush closable windows. Any future
        # edge has ts >= max_ts - lateness (the lateness bound), hence
        # lands in window >= upto: everything below can close.
        hi = int(ts[ok].max())
        max_ts = hi if max_ts is None else max(max_ts, hi)
        upto = (max_ts - lateness) // window_ms
        if closed_upto is None or upto > closed_upto:
            closed_upto = upto
        if pending:
            yield from flush(closed_upto)
    yield from flush(None)
