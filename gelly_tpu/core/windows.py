"""Shared tumbling-window event iterator.

One implementation of the reference's tumbling time-window semantics
(``timeWindow(timeMillis)`` / ``slice``; ascending-timestamp contract with
allowedLateness=0) consumed by both the aggregation engine's ``window_ms``
path and the SnapshotStream buffer — a single place for window-boundary and
late-edge policy.

Yields events in stream order:

- ``("edges", window, masked_chunk, n_valid)`` — a chunk masked down to the
  edges of ``window`` (n_valid = host count of live edges in the mask);
- ``("close", window, None, 0)`` — emitted when a later window's first edge
  arrives (windows with no data never fire, Flink semantics) and once at
  end-of-stream for the final partial window.

Late edges (timestamp before the currently open window) are dropped and
counted in ``stats["late_edges"]``.
"""

from __future__ import annotations

from typing import Iterable, Iterator

import jax.numpy as jnp
import numpy as np

from .chunk import EdgeChunk


def tumbling_window_events(
    chunks: Iterable[EdgeChunk], window_ms: int, stats: dict | None = None,
    initial_window: int | None = None,
) -> Iterator[tuple]:
    """``initial_window`` seeds the open window (checkpoint resume: edges of
    earlier, already-emitted windows count as late instead of re-opening)."""
    if stats is None:
        stats = {}
    stats.setdefault("late_edges", 0)
    current = initial_window
    dirty = False
    for c in chunks:
        ts = np.asarray(c.ts)
        ok = np.asarray(c.valid)
        if not ok.any():
            continue
        tw = ts // window_ms
        if current is not None:
            n_late = int((ok & (tw < current)).sum())
            if n_late:
                stats["late_edges"] += n_late
                ok = ok & (tw >= current)
        for w in np.unique(tw[ok]).tolist():
            if current is None:
                current = w
            if w > current:
                if dirty:
                    yield ("close", current, None, 0)
                    dirty = False
                current = w
            mask = ok & (tw == w)
            # Host chunks stay host (an np mask keeps valid numpy); device
            # chunks get a device mask to avoid an implicit H2D per op.
            m = mask if c.is_host() else jnp.asarray(mask)
            yield ("edges", w, c.mask(m), int(mask.sum()))
            dirty = True
    if dirty:
        yield ("close", current, None, 0)
