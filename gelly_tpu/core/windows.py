"""Shared tumbling-window event iterator.

One implementation of the reference's tumbling time-window semantics
(``timeWindow(timeMillis)`` / ``slice``; ascending-timestamp contract with
allowedLateness=0) consumed by both the aggregation engine's ``window_ms``
path and the SnapshotStream buffer — a single place for window-boundary and
late-edge policy.

Yields events in stream order:

- ``("edges", window, masked_chunk, n_valid)`` — a chunk masked down to the
  edges of ``window`` (n_valid = host count of live edges in the mask);
- ``("close", window, None, 0)`` — emitted when a later window's first edge
  arrives (windows with no data never fire, Flink semantics) and once at
  end-of-stream for the final partial window.

Late edges (timestamp before the currently open window) are dropped and
counted in ``stats["late_edges"]``.

``allowed_lateness`` (ms) enables a bounded reorder buffer: window ``w``
closes only once the watermark ``max_ts_seen - allowed_lateness`` passes
its end, so edges shuffled within the lateness bound land in their correct
window (the reference's ascending-timestamp contract,
``M/SimpleEdgeStream.java:86-90``, makes lateness impossible; this is the
relaxation for out-of-order sources). Edges later than the bound are still
dropped + counted. With lateness on, a window's edges are emitted in
*arrival* order just before its close — identical final window contents to
the sorted stream, but order-sensitive per-window folds observe arrival
order, not timestamp order.
"""

from __future__ import annotations

from typing import Iterable, Iterator

import jax.numpy as jnp
import numpy as np

from .chunk import EdgeChunk


def tumbling_window_events(
    chunks: Iterable[EdgeChunk], window_ms: int, stats: dict | None = None,
    initial_window: int | None = None, allowed_lateness: int = 0,
    state_handle: dict | None = None,
    initial_state: dict | None = None,
) -> Iterator[tuple]:
    """``initial_window`` seeds the open window (checkpoint resume: edges of
    earlier, already-emitted windows count as late instead of re-opening).

    With lateness, ``state_handle`` (a caller-provided dict) gains an
    ``"export"`` callable returning the live reorder-buffer state —
    ``(wins list, [compact EdgeChunk per open window], closed_upto,
    max_ts)`` — for checkpointing, and ``initial_state`` (a prior export,
    re-shaped by the engine) seeds the buffer on resume so in-flight late
    edges survive a restart.
    """
    if allowed_lateness:
        yield from _tumbling_with_lateness(
            chunks, window_ms, stats if stats is not None else {},
            initial_window, allowed_lateness, state_handle, initial_state,
        )
        return
    if stats is None:
        stats = {}
    stats.setdefault("late_edges", 0)
    current = initial_window
    dirty = False
    for c in chunks:
        ts = np.asarray(c.ts)
        ok = np.asarray(c.valid)
        if not ok.any():
            continue
        tw = ts // window_ms
        if current is not None:
            n_late = int((ok & (tw < current)).sum())
            if n_late:
                stats["late_edges"] += n_late
                ok = ok & (tw >= current)
        for w in np.unique(tw[ok]).tolist():
            if current is None:
                current = w
            if w > current:
                if dirty:
                    yield ("close", current, None, 0)
                    dirty = False
                current = w
            mask = ok & (tw == w)
            # Host chunks stay host (an np mask keeps valid numpy); device
            # chunks get a device mask to avoid an implicit H2D per op.
            m = mask if c.is_host() else jnp.asarray(mask)
            yield ("edges", w, c.mask(m), int(mask.sum()))
            dirty = True
    if dirty:
        yield ("close", current, None, 0)


def _tumbling_with_lateness(
    chunks: Iterable[EdgeChunk], window_ms: int, stats: dict,
    initial_window: int | None, lateness: int,
    state_handle: dict | None = None,
    initial_state: dict | None = None,
) -> Iterator[tuple]:
    """Watermark-gated reorder buffer (see module docstring).

    ``pending`` holds (chunk, index-array) pairs per open window — chunks
    are immutable by contract (:func:`~gelly_tpu.core.chunk.make_chunk`),
    so buffering references is safe, and masks are compacted to indices so
    buffer memory is ∝ actually-buffered edges, not chunk capacity ×
    overlaps. Windows flush in ascending order once the watermark passes
    their end; all of a window's edge events are emitted (arrival order)
    immediately before its close event, so consumers see the same monotone
    window sequence as the zero-lateness iterator.

    Buffer bound: at most ``ceil((allowed_lateness + chunk_ts_span) /
    window_ms) + 1`` windows are open at once — the watermark trails
    max_ts by exactly the lateness, plus whatever window range a single
    chunk's own timestamps span before the post-chunk flush — each holding
    references to the chunks that touched it; worst-case host memory ∝
    that window count × chunk size. The live
    footprint is observable via ``stats["buffered_edges"]`` /
    ``stats["open_windows"]``, updated as edges enter and leave the
    buffer.
    """
    stats.setdefault("late_edges", 0)
    stats["buffered_edges"] = 0
    stats["open_windows"] = 0
    pending: dict[int, list] = {}
    # Windows below this are closed: their edges are late (drop + count).
    closed_upto = initial_window if initial_window is not None else None
    max_ts = None
    if initial_state is not None:
        # Resume: re-seed the reorder buffer from a checkpoint export —
        # one compact chunk per open window, every row live.
        closed_upto = initial_state.get("closed_upto", closed_upto)
        max_ts = initial_state.get("max_ts", max_ts)
        for w, ch in zip(initial_state["wins"], initial_state["chunks"]):
            idx = np.arange(ch.capacity, dtype=np.int32)
            pending[int(w)] = [(ch, idx)]
            stats["buffered_edges"] += ch.capacity
        stats["open_windows"] = len(pending)

    def export_state():
        wins = sorted(pending)
        out_chunks = []
        for w in wins:
            parts = pending[w]
            out_chunks.append(EdgeChunk(*(
                np.concatenate([
                    np.asarray(getattr(ch, name))[idx]
                    for ch, idx in parts
                ])
                for name in EdgeChunk._fields
            )))
        return {
            "wins": wins, "chunks": out_chunks,
            "closed_upto": closed_upto, "max_ts": max_ts,
        }

    if state_handle is not None:
        state_handle["export"] = export_state

    def flush(upto):
        for w in sorted(w for w in pending if upto is None or w < upto):
            for ch, idx in pending.pop(w):
                m = np.zeros(ch.capacity, bool)
                m[idx] = True
                mm = m if ch.is_host() else jnp.asarray(m)
                stats["buffered_edges"] -= idx.shape[0]
                yield ("edges", w, ch.mask(mm), idx.shape[0])
            stats["open_windows"] = len(pending)
            yield ("close", w, None, 0)

    for c in chunks:
        ts = np.asarray(c.ts)
        ok = np.asarray(c.valid)
        if not ok.any():
            continue
        tw = ts // window_ms
        # Lateness is judged against the watermark as it stood BEFORE this
        # chunk: an edge is late only if its window already closed. (Using
        # this chunk's own max_ts first would make a chunk spanning more
        # than the lateness bound drop its own earlier edges — even on a
        # perfectly sorted stream.)
        if closed_upto is not None:
            n_late = int((ok & (tw < closed_upto)).sum())
            if n_late:
                stats["late_edges"] += n_late
                ok = ok & (tw >= closed_upto)
            if not ok.any():
                continue
        for w in np.unique(tw[ok]).tolist():
            idx = np.nonzero(ok & (tw == w))[0].astype(np.int32)
            pending.setdefault(w, []).append((c, idx))
            stats["buffered_edges"] += idx.shape[0]
        stats["open_windows"] = len(pending)
        # Now advance the watermark and flush closable windows. Any future
        # edge has ts >= max_ts - lateness (the lateness bound), hence
        # lands in window >= upto: everything below can close.
        hi = int(ts[ok].max())
        max_ts = hi if max_ts is None else max(max_ts, hi)
        upto = (max_ts - lateness) // window_ms
        if closed_upto is None or upto > closed_upto:
            closed_upto = upto
        if pending:
            yield from flush(closed_upto)
    yield from flush(None)


class PaneRing:
    """Two-stack suffix aggregation over the last ``window_panes`` pane
    summaries (the FOO/DABA shape): a sliding window of W panes answered
    in O(1) amortized ``combine`` dispatches per pane close.

    The temporal engine folds edges into the CURRENT pane with the
    ordinary compiled fold; at each pane boundary the closed pane summary
    is :meth:`push`-ed here and :meth:`query` returns the combine of the
    last ``min(live, W)`` panes — never a W-pane re-merge and never a
    replay. Structure:

    - ``_back`` — raw panes in arrival order, with ``_back_agg`` the
      running combine of all of them (one combine per push);
    - ``_front`` — ``(raw_pane, suffix_agg)`` pairs where each entry's
      ``suffix_agg`` is the combine of that pane and every YOUNGER front
      pane, so evicting the oldest pane is a stack pop;
    - when the front empties, the back flips into it (one combine per
      moved pane — each pane is moved at most once, hence O(1)
      amortized; ``combines`` counts every dispatch so tests/bench can
      assert the amortization contract).

    ``combine`` must be associative with ``init``-shaped identities (the
    plan's ``SummaryAggregation.combine``, pre-jitted by the engine).
    Raw panes are kept on BOTH stacks — they are the checkpoint payload
    (:meth:`export_panes`) and the rebuild source after a TTL
    permutation (:meth:`reload`).
    """

    def __init__(self, window_panes: int, combine, on_combine=None):
        if window_panes < 1:
            raise ValueError(
                f"window_panes must be >= 1, got {window_panes}")
        self.window_panes = int(window_panes)
        self._combine = combine
        self._on_combine = on_combine  # optional hook: called per dispatch
        self._front: list = []   # (raw pane, suffix agg), oldest last
        self._back: list = []    # raw panes, oldest first
        self._back_agg = None
        self.panes_closed = 0    # total panes ever pushed
        self.combines = 0        # total combine dispatches ever issued

    # ------------------------------------------------------------- internals

    def _comb(self, a, b):
        self.combines += 1
        if self._on_combine is not None:
            self._on_combine(1)
        return self._combine(a, b)

    def _flip(self):
        # Move the back panes into the front stack with precomputed
        # suffix aggregates: iterate youngest -> oldest so each entry's
        # agg covers itself plus every younger pane. One combine per
        # moved pane; each pane flips at most once in its lifetime.
        agg = None
        for pane in reversed(self._back):
            agg = pane if agg is None else self._comb(pane, agg)
            self._front.append((pane, agg))
        self._back = []
        self._back_agg = None

    # ------------------------------------------------------------------- api

    @property
    def live(self) -> int:
        """Panes currently inside the window (<= window_panes)."""
        return len(self._front) + len(self._back)

    def push(self, pane) -> None:
        """Close a pane into the ring; evicts the oldest pane once the
        ring holds ``window_panes``. O(1) amortized combines."""
        if self.live >= self.window_panes:
            if not self._front:
                self._flip()
            self._front.pop()
        self._back.append(pane)
        self._back_agg = (
            pane if self._back_agg is None
            else self._comb(self._back_agg, pane)
        )
        self.panes_closed += 1

    def query(self):
        """Combine of every live pane (None when empty): at most ONE
        combine dispatch on top of the maintained stack aggregates."""
        front_agg = self._front[-1][1] if self._front else None
        if front_agg is None:
            return self._back_agg
        if self._back_agg is None:
            return front_agg
        return self._comb(front_agg, self._back_agg)

    def export_panes(self) -> list:
        """Raw live pane summaries, oldest -> newest — the checkpoint
        payload (stack aggregates are derived state and are NOT
        exported; :meth:`reload` rebuilds them deterministically)."""
        return [p for p, _ in reversed(self._front)] + list(self._back)

    def reload(self, panes: list, panes_closed: int) -> None:
        """Rebuild from raw panes (oldest -> newest), e.g. checkpoint
        resume or a TTL compaction remap. Stack aggregates rebuild
        canonically with all panes on the back — every summary combine
        in this engine is an associative integer merge (min-label
        forests / counter adds), so the regrouping is emission-
        invariant; the next eviction simply pays one flip."""
        if len(panes) > self.window_panes:
            raise ValueError(
                f"{len(panes)} panes exceed the {self.window_panes}-pane "
                "window")
        self._front = []
        self._back = list(panes)
        self._back_agg = None
        for pane in self._back:
            self._back_agg = (
                pane if self._back_agg is None
                else self._comb(self._back_agg, pane)
            )
        self.panes_closed = int(panes_closed)
