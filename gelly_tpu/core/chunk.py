"""Fixed-capacity COO edge chunks — the unit of streaming on TPU.

The reference (gelly-streaming) represents the stream as a Flink
``DataStream<Edge<K,EV>>`` of one-record events (``M/SimpleEdgeStream.java:55-90``).
A TPU cannot efficiently process one edge at a time: everything under ``jit`` is
traced once over static shapes, and throughput comes from batched, masked array
ops. So the atomic unit here is an :class:`EdgeChunk`: a fixed-capacity struct of
arrays holding up to ``capacity`` edges, padded with an invalid mask. Every
stream transform is a pure ``EdgeChunk -> EdgeChunk`` function, jittable and
fuseable by XLA.

Each edge carries two id representations:

- ``raw_src`` / ``raw_dst``: the external vertex ids (at their source
  integer width, up to 64-bit), which user UDFs (mapEdges / filterEdges /
  filterVertices predicates) observe — matching the reference where UDFs
  see the original ``K`` ids.
- ``src`` / ``dst``: dense ``i32`` slots assigned by a
  :class:`~gelly_tpu.core.vertices.VertexTable` at ingest; all summary kernels
  index fixed-shape state arrays with these. This replaces the reference's
  hash-map keying of arbitrary ``K`` ids.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

# Event types, mirroring the reference's EventType enum
# (/root/reference/src/main/java/org/apache/flink/graph/streaming/EventType.java:24-27).
EDGE_ADDITION = np.int8(0)
EDGE_DELETION = np.int8(1)


class EdgeChunk(NamedTuple):
    """A fixed-capacity batch of edges in structure-of-arrays COO layout.

    Fields are always present so the pytree structure is static under jit:

    - ``src``, ``dst``: ``i32[C]`` dense vertex slots (padding entries are 0).
    - ``raw_src``, ``raw_dst``: external vertex ids at their source integer
      width (``i64`` for file/table ingest; narrower for identity streams
      whose source arrays already are).
    - ``val``: ``EV[C]`` or ``EV[C, k]`` edge values (default ``f32`` ones).
    - ``ts``: ``i64[C]`` event-time or ingestion-time timestamps (ms).
    - ``event``: ``i8[C]`` — 0 = addition, 1 = deletion (EventType equivalent).
    - ``valid``: ``bool[C]`` — mask of live edges; everything else is padding.

    The edge axis is axis 0 of every field.
    """

    src: jax.Array
    dst: jax.Array
    raw_src: jax.Array
    raw_dst: jax.Array
    val: jax.Array
    ts: jax.Array
    event: jax.Array
    valid: jax.Array

    @property
    def capacity(self) -> int:
        return self.src.shape[0]

    def num_valid(self) -> jax.Array:
        return jnp.sum(self.valid.astype(jnp.int32))

    def reverse(self) -> "EdgeChunk":
        """Swap src/dst (GraphStream.reverse, M/SimpleEdgeStream.java:328-337)."""
        return self._replace(
            src=self.dst, dst=self.src, raw_src=self.raw_dst, raw_dst=self.raw_src
        )

    def undirected(self) -> "EdgeChunk":
        """Emit each edge in both directions (M/SimpleEdgeStream.java:350-361).

        Doubles the chunk capacity: the result holds ``e`` followed by
        ``e.reverse()``.
        """
        return concat_chunks(self, self.reverse())

    def mask(self, keep) -> "EdgeChunk":
        """Return the chunk with ``valid &= keep`` (filter without moving data)."""
        return self._replace(valid=self.valid & keep)

    def is_host(self) -> bool:
        return isinstance(self.src, np.ndarray)

    def to_numpy(self) -> "EdgeChunk":
        return EdgeChunk(*(np.asarray(f) for f in self))

    def compact_edges(self, raw: bool = True):
        """Host-side: drop padding, return (src, dst, val) of the valid edges."""
        c = self.to_numpy()
        m = c.valid.astype(bool)
        if raw:
            return c.raw_src[m], c.raw_dst[m], c.val[m]
        return c.src[m], c.dst[m], c.val[m]


# Shared read-only default fields, cached per (capacity, kind): chunk
# construction is on the ingest critical path, and re-allocating ones/zeros
# per chunk costs tens of ms at multi-million-edge chunk sizes. Consumers
# treat chunk fields as immutable (pure-functional discipline), so sharing
# is safe.
_const_cache: dict = {}


def _const(cap: int, kind: str, dtype) -> np.ndarray:
    key = (cap, kind, np.dtype(dtype))
    out = _const_cache.get(key)
    if out is None:
        if kind == "ones":
            out = np.ones((cap,), dtype)
        else:
            out = np.zeros((cap,), dtype)
        out.setflags(write=False)
        _const_cache[key] = out
    return out


def make_chunk(
    src,
    dst,
    raw_src=None,
    raw_dst=None,
    val=None,
    ts=None,
    event=None,
    capacity: int | None = None,
    val_dtype=jnp.float32,
    device: bool = True,
) -> EdgeChunk:
    """Build a padded :class:`EdgeChunk` from host arrays.

    ``capacity`` defaults to ``len(src)``; when larger, the tail is padding with
    ``valid=False``. Padding slots use vertex 0 / value 0 and are never observed
    by kernels, which must respect ``valid``. ``raw_src``/``raw_dst`` default to
    the slot values (identity densification).

    ``device=False`` keeps the fields as numpy: the H2D transfer then happens
    lazily when a jitted consumer first touches the chunk, and host-side
    window logic (timestamp reads, direction transforms) costs no device
    round-trips — the right mode for ingest sources. Full chunks (n ==
    capacity) of already-right-dtype arrays are zero-copy views; default
    val/event/valid fields are shared cached constants.

    No-mutation contract: on the zero-copy fast path the returned chunk
    ALIASES the caller's arrays (numpy offers no way to write-protect the
    caller's buffer through a view). A source must therefore not reuse or
    mutate its input buffers after yielding a chunk built from them — the
    chunk may still be in flight on the prefetch/ingest pipeline. Sources
    that recycle buffers must pass copies.
    """
    src = np.asarray(src)
    dst = np.asarray(dst)
    n = src.shape[0]
    if dst.shape[0] != n:
        raise ValueError(f"src/dst length mismatch: {n} vs {dst.shape[0]}")
    cap = capacity if capacity is not None else n
    if cap < n:
        raise ValueError(f"capacity {cap} < number of edges {n}")

    def pad(a, dtype):
        dtype = np.dtype(dtype)
        a = np.asarray(a)
        if a.dtype == dtype and a.shape[0] == cap:
            return a  # zero-copy fast path (full chunk, right dtype)
        a = a.astype(dtype, copy=False)
        if a.shape[0] == cap:
            return a  # full chunk, one dtype-conversion pass, no re-pad
        out = np.zeros((cap,) + a.shape[1:], dtype=dtype)
        out[:n] = a
        return out

    raw_src = src if raw_src is None else np.asarray(raw_src)
    raw_dst = dst if raw_dst is None else np.asarray(raw_dst)
    # Raw ids keep their source integer width (i64 only when a source is
    # i64): identity-table streams then slice raw fields zero-copy instead
    # of astype-copying 16 bytes/edge on the ingest thread. Consumers see
    # raw ids only through user fns / decode, which are width-agnostic.
    # Both fields share the promoted width so a wider raw_dst never
    # truncates.

    def _int_width(a):
        return a.dtype if np.issubdtype(a.dtype, np.integer) else np.int64

    raw_dtype = np.promote_types(_int_width(raw_src), _int_width(raw_dst))
    if val is None:
        val = (
            _const(cap, "ones", val_dtype)
            if n == cap
            else np.ones((n,), dtype=np.dtype(val_dtype))
        )
    ts = np.arange(n, dtype=np.int64) if ts is None else ts
    event = _const(cap, "zeros", np.int8) if event is None else pad(event, np.int8)
    if n == cap:
        valid = _const(cap, "ones", bool)
    else:
        valid = np.zeros((cap,), dtype=bool)
        valid[:n] = True
    put = jnp.asarray if device else (lambda a: a)
    return EdgeChunk(
        src=put(pad(src, np.int32)),
        dst=put(pad(dst, np.int32)),
        raw_src=put(pad(raw_src, raw_dtype)),
        raw_dst=put(pad(raw_dst, raw_dtype)),
        val=put(pad(val, np.dtype(val_dtype))),
        ts=put(pad(ts, np.int64)),
        event=put(event),
        valid=put(valid),
    )


def empty_chunk(capacity: int, val_dtype=jnp.float32, val_shape=()) -> EdgeChunk:
    return EdgeChunk(
        src=jnp.zeros((capacity,), jnp.int32),
        dst=jnp.zeros((capacity,), jnp.int32),
        raw_src=jnp.zeros((capacity,), jnp.int64),
        raw_dst=jnp.zeros((capacity,), jnp.int64),
        val=jnp.zeros((capacity,) + val_shape, val_dtype),
        ts=jnp.zeros((capacity,), jnp.int64),
        event=jnp.zeros((capacity,), jnp.int8),
        valid=jnp.zeros((capacity,), bool),
    )


def concat_chunks(a: EdgeChunk, b: EdgeChunk) -> EdgeChunk:
    """Concatenate along the edge axis (capacity = a.capacity + b.capacity).
    Host chunks concatenate in numpy (no device round-trip)."""
    xp = np if a.is_host() and b.is_host() else jnp
    return EdgeChunk(*(xp.concatenate([x, y], axis=0) for x, y in zip(a, b)))


def split_chunk_host(chunk: EdgeChunk, parts: int) -> list[EdgeChunk]:
    """Split a HOST chunk into ``parts`` contiguous slices along the edge
    axis (padding the tail with invalid entries when the capacity is not
    divisible) — the host-side analog of ``parallel.partition.split_chunk``
    for staging paths that compress before the H2D transfer (the mesh
    windowed codec). Slices are views where no padding is needed."""
    n = np.asarray(chunk.src).shape[0]
    per = -(-max(n, parts) // parts)
    pad = per * parts - n

    def prep(name, a):
        a = np.asarray(a)
        if pad:
            fill = np.zeros((pad,) + a.shape[1:], a.dtype)
            a = np.concatenate([a, fill])
        return a

    fields = {name: prep(name, getattr(chunk, name))
              for name in chunk._fields}
    return [
        EdgeChunk(**{
            k: v[s * per:(s + 1) * per] for k, v in fields.items()
        })
        for s in range(parts)
    ]
