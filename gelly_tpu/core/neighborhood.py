"""NeighborhoodStream — growing adjacency snapshots on device.

TPU-native re-design of ``SimpleEdgeStream.buildNeighborhood``
(``M/SimpleEdgeStream.java:531-560``): the reference keeps a per-key
``HashMap<K, TreeSet<K>>`` and re-emits a vertex's adjacency set after every
edge. Here the adjacency is a dense device ``bool[N, N]`` matrix updated by
masked scatter, and emission is chunk-grained: one snapshot per processed
chunk. Set membership, intersection (the triangle-count hot op,
``M/example/ExactTriangleCount.java:74-116``) and neighbor iteration become
row gathers / elementwise ANDs / popcounts — MXU/VPU-friendly, no pointer
chasing.

Memory: N² bytes (bool). N = the stream's vertex capacity by default; cap it
via ``capacity`` for large id spaces (the exact-triangle path is meant for
graphs that fit; the sampled estimators cover the rest).
"""

from __future__ import annotations

from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np

from .chunk import EdgeChunk
from functools import partial


@partial(jax.jit, static_argnames=("directed",))
def _adj_step(adj, c: EdgeChunk, directed: bool):
    src = jnp.where(c.valid, c.src, 0)
    dst = jnp.where(c.valid, c.dst, 0)
    adj = adj.at[src, dst].max(c.valid, mode="drop")
    if not directed:
        adj = adj.at[dst, src].max(c.valid, mode="drop")
    return adj


@partial(jax.jit, static_argnames=("directed", "max_degree"))
def _row_step(nbr, deg, over, c: EdgeChunk, directed: bool, max_degree: int):
    """Capped-degree row-table insert with set semantics (TreeSet parity:
    duplicates are no-ops; self-loops insert like the dense path and the
    reference's map-of-sets). Sequential within the chunk so in-chunk
    duplicates dedupe too."""
    from ..ops.rowtable import row_insert

    def step(carry, inp):
        u, v, ok = inp
        carry = row_insert(*carry, u, v, ok, max_degree)
        if not directed:
            # For self-loops the second direction dedupes to a no-op.
            carry = row_insert(*carry, v, u, ok, max_degree)
        return carry, None

    (nbr, deg, over), _ = jax.lax.scan(
        step, (nbr, deg, over), (c.src, c.dst, c.valid)
    )
    return nbr, deg, over


class NeighborhoodStream:
    """Stream of growing adjacency snapshots (buildNeighborhood analog).

    ``directed=False`` (the reference's default usage) stores both directions
    of every edge, matching ``buildNeighborhood(false)`` routing through
    ``undirected()`` (``M/SimpleEdgeStream.java:533-535``).
    """

    def __init__(self, stream, directed: bool = False,
                 capacity: int | None = None,
                 max_degree: int | None = None):
        self.stream = stream
        self.directed = directed
        self.capacity = (
            int(capacity) if capacity is not None
            else stream.ctx.vertex_capacity
        )
        # max_degree switches to the capped-degree row table: O(N*D)
        # memory, the N >= 1M buildNeighborhood path (the reference's
        # TreeSet adjacency handles arbitrary N,
        # M/summaries/AdjacencyListGraph.java:31). Degree overflow raises —
        # never a silently truncated neighborhood.
        self.max_degree = max_degree

    def __iter__(self) -> Iterator[jax.Array]:
        """Yield the adjacency snapshot after each chunk (chunk-grained
        emission; the reference emits per edge — documented deviation, final
        state identical). Dense mode yields bool[N, N]; sparse mode yields
        (nbr i32[N, D], deg i32[N])."""
        n = self.capacity
        if self.max_degree is None:
            adj = jnp.zeros((n, n), bool)
            for c in self.stream:
                self._check_range(c)
                adj = _adj_step(adj, c, self.directed)
                yield adj
            return
        nbr = jnp.full((n, self.max_degree), -1, jnp.int32)
        deg = jnp.zeros((n,), jnp.int32)
        over = jnp.zeros((), jnp.int32)
        for c in self.stream:
            self._check_range(c)
            nbr, deg, over = _row_step(
                nbr, deg, over, c, self.directed, self.max_degree
            )
            # Synchronous overflow check: consumers act on every yielded
            # snapshot, so a truncated row must never be observable (the
            # one-chunk-deferred pattern used by the sparse triangle stream
            # would leak one). Costs one host sync per chunk.
            if int(over):
                raise self._overflow_error(int(over))
            yield nbr, deg

    def final_adjacency(self):
        """Drained adjacency; cached so repeated queries (neighbors_of) don't
        re-read the stream and rebuild the matrix/table."""
        if getattr(self, "_final", None) is None:
            adj = None
            for adj in self:
                pass
            if adj is None:
                if self.max_degree is None:
                    adj = jnp.zeros((self.capacity, self.capacity), bool)
                else:
                    adj = (
                        jnp.full((self.capacity, self.max_degree), -1,
                                 jnp.int32),
                        jnp.zeros((self.capacity,), jnp.int32),
                    )
            self._final = adj
        return self._final

    def _overflow_error(self, n: int) -> ValueError:
        return ValueError(
            f"{n} neighbor inserts exceeded max_degree {self.max_degree}; "
            f"raise max_degree or use the dense path"
        )

    def _check_range(self, c: EdgeChunk):
        # Guard against silent drop when capacity < stream vertex space.
        if self.capacity < self.stream.ctx.vertex_capacity:
            m = np.asarray(c.valid)
            hi = max(
                int(np.asarray(c.src)[m].max(initial=0)),
                int(np.asarray(c.dst)[m].max(initial=0)),
            )
            if hi >= self.capacity:
                raise ValueError(
                    f"vertex slot {hi} exceeds neighborhood capacity "
                    f"{self.capacity}"
                )

    def neighbors_of(self, raw_id: int) -> list[int]:
        """Host query: sorted raw neighbor ids in the final adjacency —
        the TreeSet view (M/SimpleEdgeStream.java:544-551)."""
        ctx = self.stream.ctx
        adj = self.final_adjacency()  # drains first: the table fills at ingest
        slot = int(ctx.table.lookup(np.array([raw_id]))[0])
        if slot < 0:
            return []
        if self.max_degree is None:
            row = np.asarray(adj[slot])
            nbrs = np.nonzero(row)[0]
        else:
            nbr, deg = adj
            row = np.asarray(nbr[slot])
            nbrs = row[: int(deg[slot])]
        return sorted(ctx.decode(nbrs).tolist())
