"""Host-side vertex-id densification.

The reference keys state by arbitrary ``K`` ids in per-subtask hash maps
(e.g. ``DegreeMapFunction``'s ``HashMap<K, Long>``,
``M/SimpleEdgeStream.java:461-478``, and ``DisjointSet``'s ``HashMap<R,R>``,
``M/summaries/DisjointSet.java:28-29``). On TPU, summaries are fixed-shape
arrays indexed by a dense ``i32`` slot, so raw ids are translated once at
ingest on the host and never appear on device.

Two tables:

- :class:`VertexTable` — growable dict-based raw→slot mapping for arbitrary
  (sparse / 64-bit / hashed) id spaces.
- :class:`IdentityVertexTable` — zero-cost pass-through when ids are already
  dense integers in ``[0, capacity)`` (the fast path for benchmark graphs).
"""

from __future__ import annotations

import numpy as np


class VertexTable:
    """Growable raw-id → dense-slot dictionary (host side).

    ``capacity`` (when set, e.g. by the stream context binding this table)
    bounds the slot space; encoding more distinct ids than that raises instead
    of silently corrupting device summaries sized to the capacity.
    """

    def __init__(self, capacity: int | None = None):
        self._map: dict[int, int] = {}
        self._rev: list[int] = []
        self.capacity = capacity

    def __len__(self) -> int:
        return len(self._rev)

    @property
    def num_vertices(self) -> int:
        return len(self._rev)

    def encode(self, raw_ids: np.ndarray) -> np.ndarray:
        """Map raw ids to dense slots, assigning new slots for unseen ids."""
        raw_ids = np.asarray(raw_ids).ravel()
        out = np.empty(raw_ids.shape[0], dtype=np.int32)
        m = self._map
        rev = self._rev
        cap = self.capacity
        for i, r in enumerate(raw_ids.tolist()):
            s = m.get(r)
            if s is None:
                s = len(rev)
                if cap is not None and s >= cap:
                    raise ValueError(
                        f"vertex table overflow: more than {cap} distinct "
                        f"vertex ids in the stream (raise vertex_capacity)"
                    )
                m[r] = s
                rev.append(r)
            out[i] = s
        return out

    def lookup(self, raw_ids: np.ndarray) -> np.ndarray:
        """Map raw ids to slots; unseen ids map to -1."""
        raw_ids = np.asarray(raw_ids).ravel()
        m = self._map
        return np.fromiter(
            (m.get(r, -1) for r in raw_ids.tolist()), dtype=np.int32,
            count=raw_ids.shape[0],
        )

    def decode(self, slots: np.ndarray) -> np.ndarray:
        """Map dense slots back to raw ids."""
        rev = np.asarray(self._rev, dtype=np.int64)
        return rev[np.asarray(slots)]


class IdentityVertexTable:
    """Pass-through table for ids already dense in ``[0, capacity)``."""

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self._max_seen = -1

    def __len__(self) -> int:
        return self._max_seen + 1

    @property
    def num_vertices(self) -> int:
        return self._max_seen + 1

    def encode(self, raw_ids: np.ndarray) -> np.ndarray:
        raw_ids = np.asarray(raw_ids).ravel()
        if raw_ids.size:
            hi = int(raw_ids.max())
            if hi >= self.capacity:
                raise ValueError(
                    f"vertex id {hi} out of range for capacity {self.capacity}"
                )
            self._max_seen = max(self._max_seen, hi)
        return raw_ids.astype(np.int32)

    def lookup(self, raw_ids: np.ndarray) -> np.ndarray:
        return np.asarray(raw_ids).ravel().astype(np.int32)

    def decode(self, slots: np.ndarray) -> np.ndarray:
        return np.asarray(slots).astype(np.int64)
