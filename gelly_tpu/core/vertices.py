"""Host-side vertex-id densification.

The reference keys state by arbitrary ``K`` ids in per-subtask hash maps
(e.g. ``DegreeMapFunction``'s ``HashMap<K, Long>``,
``M/SimpleEdgeStream.java:461-478``, and ``DisjointSet``'s ``HashMap<R,R>``,
``M/summaries/DisjointSet.java:28-29``). On TPU, summaries are fixed-shape
arrays indexed by a dense ``i32`` slot, so raw ids are translated once at
ingest on the host and never appear on device.

Two tables:

- :class:`VertexTable` — growable raw→slot mapping (sorted-array +
  ``searchsorted``, fully vectorized) for arbitrary sparse/64-bit id spaces.
- :class:`IdentityVertexTable` — zero-cost pass-through when ids are already
  dense integers in ``[0, capacity)`` (the fast path for benchmark graphs).
"""

from __future__ import annotations

import threading

import numpy as np


class VertexTable:
    """Growable raw-id → dense-slot dictionary (host side).

    ``capacity`` (when set, e.g. by the stream context binding this table)
    bounds the slot space; encoding more distinct ids than that raises instead
    of silently corrupting device summaries sized to the capacity.

    Internals are fully vectorized (no per-id Python loop): known ids live in
    two sorted arrays probed with ``searchsorted`` — a large ``main`` region
    and a small ``pending`` region that absorbs new ids cheaply (O(pending)
    insert) and is merged into main only when it outgrows a threshold, so a
    long stream of gradually-arriving ids costs amortized O(new) per batch
    instead of an O(table) rebuild every chunk.
    """

    _MERGE_THRESHOLD = 1 << 16

    def __init__(self, capacity: int | None = None):
        self._sorted_ids = np.empty(0, np.int64)  # main region, sorted
        self._sorted_slots = np.empty(0, np.int32)  # slot of _sorted_ids[i]
        self._pend_ids = np.empty(0, np.int64)  # pending region, sorted
        self._pend_slots = np.empty(0, np.int32)
        self._rev = np.empty(0, np.int64)  # slot -> raw id
        self.capacity = capacity
        # encode runs on the prefetch thread while consumers call
        # lookup/decode from the main thread; the multi-array updates are
        # not atomic, so all table accesses serialize on this lock.
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return int(self._rev.shape[0])

    @property
    def num_vertices(self) -> int:
        return len(self)

    @staticmethod
    def _probe(ids: np.ndarray, slots: np.ndarray, q: np.ndarray) -> np.ndarray:
        """Slots for ``q`` against one sorted region; -1 where absent."""
        if ids.shape[0] == 0:
            return np.full(q.shape[0], -1, np.int32)
        pos = np.minimum(np.searchsorted(ids, q), ids.shape[0] - 1)
        return np.where(ids[pos] == q, slots[pos], -1).astype(np.int32)

    def encode(self, raw_ids: np.ndarray) -> np.ndarray:
        """Map raw ids to dense slots, assigning new slots for unseen ids."""
        raw = np.asarray(raw_ids).ravel().astype(np.int64)
        if raw.size == 0:
            return np.empty(0, np.int32)
        with self._lock:
            return self._encode_locked(raw)

    def _encode_locked(self, raw: np.ndarray) -> np.ndarray:
        uniq, first_idx, inv = np.unique(
            raw, return_index=True, return_inverse=True
        )
        uniq_slots = self._probe(self._sorted_ids, self._sorted_slots, uniq)
        miss = uniq_slots < 0
        if miss.any():
            uniq_slots[miss] = self._probe(
                self._pend_ids, self._pend_slots, uniq[miss]
            )
        new = uniq_slots < 0
        new_ids = uniq[new]
        if new_ids.size:
            base = self._rev.shape[0]
            if self.capacity is not None and base + new_ids.size > self.capacity:
                raise ValueError(
                    f"vertex table overflow: more than {self.capacity} "
                    f"distinct vertex ids in the stream (raise vertex_capacity)"
                )
            # Slots follow first appearance in the batch (streaming parity:
            # the reference assigns state entries in arrival order).
            order = np.argsort(first_idx[new], kind="stable")
            new_slots = np.empty(new_ids.size, np.int32)
            new_slots[order] = np.arange(
                base, base + new_ids.size, dtype=np.int32
            )
            uniq_slots[new] = new_slots
            self._rev = np.concatenate([self._rev, new_ids[order]])
            ins = np.searchsorted(self._pend_ids, new_ids)
            self._pend_ids = np.insert(self._pend_ids, ins, new_ids)
            self._pend_slots = np.insert(self._pend_slots, ins, new_slots)
            if self._pend_ids.shape[0] > self._MERGE_THRESHOLD:
                self._merge_pending()
        return uniq_slots[inv]

    def _merge_pending(self):
        ids = np.concatenate([self._sorted_ids, self._pend_ids])
        slots = np.concatenate([self._sorted_slots, self._pend_slots])
        order = np.argsort(ids, kind="stable")
        self._sorted_ids = ids[order]
        self._sorted_slots = slots[order]
        self._pend_ids = np.empty(0, np.int64)
        self._pend_slots = np.empty(0, np.int32)

    def lookup(self, raw_ids: np.ndarray) -> np.ndarray:
        """Map raw ids to slots; unseen ids map to -1."""
        raw = np.asarray(raw_ids).ravel().astype(np.int64)
        if raw.size == 0:
            return np.full(raw.shape[0], -1, np.int32)
        with self._lock:
            out = self._probe(self._sorted_ids, self._sorted_slots, raw)
            miss = out < 0
            if miss.any():
                out[miss] = self._probe(
                    self._pend_ids, self._pend_slots, raw[miss]
                )
            return out

    def decode(self, slots: np.ndarray) -> np.ndarray:
        """Map dense slots back to raw ids."""
        with self._lock:
            return self._rev[np.asarray(slots)]


class IdentityVertexTable:
    """Pass-through table for ids already dense in ``[0, capacity)``."""

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self._max_seen = -1

    def __len__(self) -> int:
        return self._max_seen + 1

    @property
    def num_vertices(self) -> int:
        return self._max_seen + 1

    def encode(self, raw_ids: np.ndarray) -> np.ndarray:
        raw_ids = np.asarray(raw_ids).ravel()
        if raw_ids.size:
            hi = int(raw_ids.max())
            if hi >= self.capacity:
                raise ValueError(
                    f"vertex id {hi} out of range for capacity {self.capacity}"
                )
            self._max_seen = max(self._max_seen, hi)
        return raw_ids.astype(np.int32, copy=False)

    def lookup(self, raw_ids: np.ndarray) -> np.ndarray:
        return np.asarray(raw_ids).ravel().astype(np.int32, copy=False)

    def decode(self, slots: np.ndarray) -> np.ndarray:
        return np.asarray(slots).astype(np.int64)
