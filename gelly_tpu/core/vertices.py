"""Host-side vertex-id densification.

The reference keys state by arbitrary ``K`` ids in per-subtask hash maps
(e.g. ``DegreeMapFunction``'s ``HashMap<K, Long>``,
``M/SimpleEdgeStream.java:461-478``, and ``DisjointSet``'s ``HashMap<R,R>``,
``M/summaries/DisjointSet.java:28-29``). On TPU, summaries are fixed-shape
arrays indexed by a dense ``i32`` slot, so raw ids are translated once at
ingest on the host and never appear on device.

Two tables:

- :class:`VertexTable` — growable raw→slot mapping (sorted-array +
  ``searchsorted``, fully vectorized) for arbitrary sparse/64-bit id spaces.
- :class:`IdentityVertexTable` — zero-cost pass-through when ids are already
  dense integers in ``[0, capacity)`` (the fast path for benchmark graphs).
"""

from __future__ import annotations

import numpy as np


class VertexTable:
    """Growable raw-id → dense-slot dictionary (host side).

    ``capacity`` (when set, e.g. by the stream context binding this table)
    bounds the slot space; encoding more distinct ids than that raises instead
    of silently corrupting device summaries sized to the capacity.

    Internals are fully vectorized (no per-id Python loop): known ids live in
    a sorted array probed with ``searchsorted``; a batch is resolved with one
    ``np.unique`` + one probe, and new ids are appended in batch-sorted order.
    """

    def __init__(self, capacity: int | None = None):
        self._sorted_ids = np.empty(0, np.int64)  # known raw ids, sorted
        self._sorted_slots = np.empty(0, np.int32)  # slot of _sorted_ids[i]
        self._rev = np.empty(0, np.int64)  # slot -> raw id
        self.capacity = capacity

    def __len__(self) -> int:
        return int(self._rev.shape[0])

    @property
    def num_vertices(self) -> int:
        return len(self)

    def encode(self, raw_ids: np.ndarray) -> np.ndarray:
        """Map raw ids to dense slots, assigning new slots for unseen ids."""
        raw = np.asarray(raw_ids).ravel().astype(np.int64)
        if raw.size == 0:
            return np.empty(0, np.int32)
        uniq, first_idx, inv = np.unique(
            raw, return_index=True, return_inverse=True
        )
        if self._sorted_ids.shape[0]:
            pos = np.minimum(
                np.searchsorted(self._sorted_ids, uniq),
                self._sorted_ids.shape[0] - 1,
            )
            known = self._sorted_ids[pos] == uniq
            uniq_slots = np.where(known, self._sorted_slots[pos], -1).astype(
                np.int32
            )
        else:
            known = np.zeros(uniq.shape[0], bool)
            uniq_slots = np.full(uniq.shape[0], -1, np.int32)
        new_ids = uniq[~known]
        if new_ids.size:
            base = self._rev.shape[0]
            if self.capacity is not None and base + new_ids.size > self.capacity:
                raise ValueError(
                    f"vertex table overflow: more than {self.capacity} "
                    f"distinct vertex ids in the stream (raise vertex_capacity)"
                )
            # Slots follow first appearance in the batch (streaming parity:
            # the reference assigns state entries in arrival order).
            order = np.argsort(first_idx[~known], kind="stable")
            new_slots = np.empty(new_ids.size, np.int32)
            new_slots[order] = np.arange(
                base, base + new_ids.size, dtype=np.int32
            )
            uniq_slots[~known] = new_slots
            self._rev = np.concatenate([self._rev, new_ids[order]])
            ins = np.searchsorted(self._sorted_ids, new_ids)
            self._sorted_ids = np.insert(self._sorted_ids, ins, new_ids)
            self._sorted_slots = np.insert(self._sorted_slots, ins, new_slots)
        return uniq_slots[inv]

    def lookup(self, raw_ids: np.ndarray) -> np.ndarray:
        """Map raw ids to slots; unseen ids map to -1."""
        raw = np.asarray(raw_ids).ravel().astype(np.int64)
        if raw.size == 0 or self._sorted_ids.shape[0] == 0:
            return np.full(raw.shape[0], -1, np.int32)
        pos = np.minimum(
            np.searchsorted(self._sorted_ids, raw), self._sorted_ids.shape[0] - 1
        )
        known = self._sorted_ids[pos] == raw
        return np.where(known, self._sorted_slots[pos], -1).astype(np.int32)

    def decode(self, slots: np.ndarray) -> np.ndarray:
        """Map dense slots back to raw ids."""
        return self._rev[np.asarray(slots)]


class IdentityVertexTable:
    """Pass-through table for ids already dense in ``[0, capacity)``."""

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self._max_seen = -1

    def __len__(self) -> int:
        return self._max_seen + 1

    @property
    def num_vertices(self) -> int:
        return self._max_seen + 1

    def encode(self, raw_ids: np.ndarray) -> np.ndarray:
        raw_ids = np.asarray(raw_ids).ravel()
        if raw_ids.size:
            hi = int(raw_ids.max())
            if hi >= self.capacity:
                raise ValueError(
                    f"vertex id {hi} out of range for capacity {self.capacity}"
                )
            self._max_seen = max(self._max_seen, hi)
        return raw_ids.astype(np.int32)

    def lookup(self, raw_ids: np.ndarray) -> np.ndarray:
        return np.asarray(raw_ids).ravel().astype(np.int32)

    def decode(self, slots: np.ndarray) -> np.ndarray:
        return np.asarray(slots).astype(np.int64)
