from .chunk import EDGE_ADDITION, EDGE_DELETION, EdgeChunk, concat_chunks, empty_chunk, make_chunk
from .io import EdgeChunkSource, TimeCharacteristic, chunks_from_edges, chunks_from_file, read_edge_list
from .vertices import IdentityVertexTable, VertexTable
