"""Mesh-sharded EXACT triangle counting — vertex-striped adjacency state.

The reference's ``ExactTriangleCount`` is a keyed two-stage dataflow:
``buildNeighborhood`` snapshots ship to each edge's key, where
``IntersectNeighborhoods`` waits for BOTH endpoints' adjacency sets,
intersects them, and emits per-vertex + global counter increments that a
keyed ``SumAndEmitCounters`` accumulates
(``M/example/ExactTriangleCount.java:74-134``). Here the same plan runs as
XLA collectives over a vertex-striped mesh (VERDICT r3 item 7):

- the capped-degree arrival-index table (``SparseTriangleCounts``'s
  ``nbr/aidx/deg`` rows) is sharded by vertex stripe — device ``d`` owns
  rows of slots ``{g : g % S == d}``, memory ∝ capacity/S per device;
- per chunk, ONE ``shard_map`` program runs three keyed exchanges
  (:func:`~gelly_tpu.parallel.partition.repartition_by_key`):

  1. **presence + append**: both directions route to their row owners;
     owners test presence (dedup vs earlier chunks), append fresh edges
     (:func:`~gelly_tpu.library.triangles._row_append` on the local
     stripe), and answer the canonical direction's freshness;
  2. **row fetch**: each fresh canonical edge (a < b) requests row(b) from
     its owner and delivers it to owner(a) — the "ship the adjacency
     snapshot to the edge's key" hop, with [L, D]-wide payload leaves
     riding the same all_to_all;
  3. **count routing**: owner(a) intersects row(a) x row(b) under the
     arrival-index closing-edge rule (only earlier-arrived edges count,
     exactly the single-device kernel's ``aidx < lim``), adds a-side
     counts locally, and routes (b, c_e) + (w, hits) increments to their
     owners; the global total is a ``psum``.

Counts are bit-identical to :class:`SparseExactTriangleStream` (asserted
in tests on the 8-virtual-device CPU mesh). Arrival indices are i32 with
no rebase on this tier (the single-device stream's ``arrival_budget``
machinery); streams beyond ~2^31 edges should shard into runs.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import segments
from ..parallel import mesh as mesh_lib
from ..parallel.mesh import SHARD_AXIS
from ..parallel.partition import (
    repartition_by_key,
    slots_per_shard,
    to_local_slot,
    unstripe,
)
from .triangles import _row_append


def _exchange_back(x: jax.Array, num_shards: int) -> jax.Array:
    cap = x.shape[0] // num_shards
    y = jax.lax.all_to_all(
        x.reshape((num_shards, cap) + x.shape[1:]),
        SHARD_AXIS, split_axis=0, concat_axis=0,
    )
    return y.reshape(x.shape)


def _sharded_exact_chunk(nbr_loc, aidx_loc, deg_loc, counts_loc, overflow,
                         a, b, idx, ok, num_shards, max_degree):
    """One shard's view of a chunk step (inside shard_map). ``a < b``
    canonical pairs, host-deduped within the chunk; ``idx`` arrival
    indices; returns updated stripes + psum'd total delta."""
    per = nbr_loc.shape[0]
    D = max_degree
    L = a.shape[0]
    me = jax.lax.axis_index(SHARD_AXIS)
    lane = me * L + jnp.arange(L, dtype=jnp.int32)

    # ---- Phase 1: presence check + append, both directions. ----
    k2 = jnp.concatenate([a, b])
    o2 = jnp.concatenate([b, a])
    i2 = jnp.concatenate([idx, idx])
    ok2 = jnp.concatenate([ok, ok])
    lane2 = jnp.concatenate([lane, jnp.full((L,), -1, jnp.int32)])
    cap1 = 2 * L
    k_r, pl_r, ok_r, _ = repartition_by_key(
        k2, (o2, i2, lane2), ok2, num_shards, cap1
    )
    o_r, i_r, lane_r = pl_r
    loc_r = to_local_slot(jnp.where(ok_r, k_r, 0), num_shards)
    present = jnp.any(
        nbr_loc[loc_r] == o_r[:, None], axis=1
    ) & ok_r
    fresh_r = ok_r & ~present
    nbr_loc, aidx_loc, deg_loc, overflow = _row_append(
        nbr_loc, aidx_loc, deg_loc, overflow,
        loc_r, o_r, jnp.where(fresh_r, i_r, segments.INT_MAX),
        fresh_r, D,
    )
    # Freshness verdict back to the canonical lanes (lane_r >= 0).
    back_ok = _exchange_back(ok_r & (lane_r >= 0), num_shards)
    back_lane = _exchange_back(lane_r, num_shards)
    back_fresh = _exchange_back(fresh_r, num_shards)
    my_lane = jnp.where(back_ok, back_lane - me * L, L)
    fresh = jnp.zeros((L,), bool).at[
        jnp.where(back_ok, my_lane, L)
    ].set(back_fresh, mode="drop")
    fresh = fresh & ok

    # ---- Phase 2: fetch row(b) to owner(a). ----
    cap2 = L
    kb_r, plb_r, okb_r, _ = repartition_by_key(
        b, (a, idx), fresh, num_shards, cap2
    )
    a_r, idx_r = plb_r
    locb = to_local_slot(jnp.where(okb_r, kb_r, 0), num_shards)
    rowb_nbr = jnp.where(okb_r[:, None], nbr_loc[locb], -1)
    rowb_aidx = jnp.where(
        okb_r[:, None], aidx_loc[locb], segments.INT_MAX
    )
    # Deliver (b, idx, row_b) to owner(a).
    cap3 = num_shards * cap2  # worst case: every request's a on one shard
    ka_r, pla_r, oka_r, _ = repartition_by_key(
        a_r, (kb_r, idx_r, rowb_nbr, rowb_aidx), okb_r, num_shards, cap3
    )
    b_f, idx_f, rbn_f, rba_f = pla_r
    loca = to_local_slot(jnp.where(oka_r, ka_r, 0), num_shards)
    rowa_nbr = nbr_loc[loca]
    rowa_aidx = aidx_loc[loca]
    lim = jnp.where(oka_r, idx_f, 0)[:, None]
    ok_u = (rowa_nbr >= 0) & (rowa_aidx < lim)
    ok_v = (rbn_f >= 0) & (rba_f < lim)
    match = (
        (rowa_nbr[:, :, None] == rbn_f[:, None, :])
        & ok_u[:, :, None] & ok_v[:, None, :]
        & oka_r[:, None, None]
    )
    c_e = jnp.sum(match, axis=(1, 2)).astype(jnp.int64)
    w_hits = jnp.sum(match, axis=2)  # [cap3, D] per row(a) entry

    # ---- Phase 3: count attribution. ----
    # a-side counts are local to this shard.
    counts_loc = counts_loc.at[
        jnp.where(oka_r, loca, per)
    ].add(c_e, mode="drop")
    # b-side + common-vertex increments route to their owners.
    upd_k = jnp.concatenate([b_f, rowa_nbr.reshape(-1)])
    upd_v = jnp.concatenate([c_e, w_hits.reshape(-1).astype(jnp.int64)])
    upd_ok = jnp.concatenate([
        oka_r & (c_e > 0),
        (ok_u & (w_hits > 0)).reshape(-1),
    ])
    cap4 = upd_k.shape[0]
    ku_r, vu_r, oku_r, _ = repartition_by_key(
        jnp.where(upd_ok, upd_k, 0), upd_v, upd_ok, num_shards, cap4
    )
    counts_loc = counts_loc.at[
        jnp.where(oku_r, to_local_slot(ku_r, num_shards), per)
    ].add(jnp.where(oku_r, vu_r, 0), mode="drop")
    total_delta = jax.lax.psum(jnp.sum(c_e), SHARD_AXIS)
    return (nbr_loc, aidx_loc, deg_loc, counts_loc, overflow, total_delta)


class ShardedExactTriangles:
    """Streaming exact triangle counts over a vertex-striped mesh.

    ``fold(stream_or_chunks)`` consumes edge chunks; ``final_counts()``
    returns ``(per-vertex dict, global total)`` identical to
    :func:`exact_triangle_count`'s. Degree overflow raises (checked per
    fold — a dropped adjacency entry could hide triangles)."""

    def __init__(self, stream, max_degree: int, capacity: int | None = None,
                 mesh=None):
        self.stream = stream
        self.mesh = mesh if mesh is not None else mesh_lib.make_mesh()
        self.S = mesh_lib.num_shards(self.mesh)
        self.n = capacity or stream.ctx.vertex_capacity
        self.per = slots_per_shard(self.n, self.S)
        self.D = max_degree
        from jax.sharding import NamedSharding, PartitionSpec as P

        sh = NamedSharding(self.mesh, P(SHARD_AXIS))
        S, per, D = self.S, self.per, self.D

        @partial(jax.jit, out_shardings=(sh,) * 4)
        def init():
            def body():
                return (
                    jnp.full((1, per, D), -1, jnp.int32),
                    jnp.full((1, per, D), segments.INT_MAX, jnp.int32),
                    jnp.zeros((1, per), jnp.int32),
                    jnp.zeros((1, per), jnp.int64),
                )

            return mesh_lib.shard_map_fn(
                self.mesh, body, in_specs=(),
                out_specs=(P(SHARD_AXIS),) * 4,
            )()

        self.nbr, self.aidx, self.deg, self.counts = init()
        self.total = 0
        self.n_seen = 0
        self.overflow = 0
        self._step = None

    def _fold_chunk(self, chunk):
        from jax.sharding import NamedSharding, PartitionSpec as P

        src = np.asarray(chunk.src)
        dst = np.asarray(chunk.dst)
        okc = np.asarray(chunk.valid)
        # Host prep, mirroring the single-device step: arrival indices
        # count every valid lane; canonical orientation; intra-chunk dedup
        # (presence vs earlier chunks is phase 1's job).
        arrivals = (self.n_seen + np.cumsum(okc.astype(np.int64)) - 1)
        self.n_seen += int(okc.sum())
        a = np.minimum(src, dst).astype(np.int32)
        b = np.maximum(src, dst).astype(np.int32)
        ok = okc & (a != b)
        pack = a.astype(np.int64) * self.n + b
        seen_first = np.zeros(ok.shape, bool)
        if ok.any():
            _, first_pos = np.unique(pack[ok], return_index=True)
            live_pos = np.nonzero(ok)[0]
            seen_first[live_pos[first_pos]] = True
        ok = ok & seen_first
        if ok.any() and (a[ok].min() < 0 or b[ok].max() >= self.n):
            raise ValueError("vertex slot out of range")

        S = self.S
        L = -(-a.shape[0] // S)
        pad = L * S - a.shape[0]
        if pad:
            a = np.concatenate([a, np.zeros(pad, np.int32)])
            b = np.concatenate([b, np.zeros(pad, np.int32)])
            arrivals = np.concatenate([arrivals, np.zeros(pad, np.int64)])
            ok = np.concatenate([ok, np.zeros(pad, bool)])
        sh = NamedSharding(self.mesh, P(SHARD_AXIS))
        key = L
        if self._step is None or self._step[0] != key:
            D, per = self.D, self.per

            @partial(jax.jit,
                     out_shardings=(sh, sh, sh, sh, None, None))
            def step(nbr, aidx, deg, counts, a_, b_, i_, ok_):
                def body(nl, al, dl, cl, aa, bb, ii, oo):
                    out = _sharded_exact_chunk(
                        nl[0], al[0], dl[0], cl[0], jnp.int32(0),
                        aa[0], bb[0], ii[0], oo[0], S, D,
                    )
                    nl2, al2, dl2, cl2, ov, td = out
                    return (nl2[None], al2[None], dl2[None], cl2[None],
                            jax.lax.psum(ov, SHARD_AXIS), td)

                return mesh_lib.shard_map_fn(
                    self.mesh, body,
                    in_specs=(P(SHARD_AXIS),) * 8,
                    out_specs=(P(SHARD_AXIS),) * 4 + (P(), P()),
                )(nbr, aidx, deg, counts, a_, b_, i_, ok_)

            self._step = (key, step)
        dev = [
            jax.device_put(x.reshape(S, L), sh)
            for x in (a, b, arrivals.astype(np.int32), ok)
        ]
        (self.nbr, self.aidx, self.deg, self.counts, ov, td) = (
            self._step[1](self.nbr, self.aidx, self.deg, self.counts, *dev)
        )
        self.overflow += int(np.asarray(ov).reshape(-1)[0])
        if self.overflow:
            raise ValueError(
                f"adjacency rows overflowed max_degree={self.D} "
                f"({self.overflow} entries dropped); raise max_degree"
            )
        self.total += int(np.asarray(td).reshape(-1)[0])

    def run(self) -> "ShardedExactTriangles":
        for chunk in self.stream:
            self._fold_chunk(chunk)
        return self

    def final_counts(self) -> dict[int, int]:
        """Per-vertex local counts by raw id, with key ``-1`` = the global
        total — the same contract as the single-device streams' (the
        reference's ``(-1, count)`` global marker,
        ``M/example/ExactTriangleCount.java:112``)."""
        counts = unstripe(np.asarray(self.counts).reshape(-1), self.S)
        out = {-1: int(self.total)}
        nz = np.nonzero(counts)[0]
        raw = self.stream.ctx.decode(nz)
        for s, r in zip(nz.tolist(), raw.tolist()):
            out[int(r)] = int(counts[s])
        return out
