"""Streaming Connected Components — the north-star algorithm.

TPU-native re-design of ``M/library/ConnectedComponents.java:41-127`` and
``ConnectedComponentsTree.java:26-36``: the per-partition ``DisjointSet``
hash-map forest becomes a dense ``i32 parent[]`` array; ``UpdateCC.foldEdges``
(per-edge ``ds.union``) becomes a whole-chunk vectorized union
(:func:`gelly_tpu.ops.unionfind.union_edges`); ``CombineCC.reduce`` (merge
smaller forest into larger) becomes either

- a **butterfly merge-tree** over ICI (`merge="tree"`) — the
  ``SummaryTreeReduce`` log-depth reduction mapped onto the slice topology, or
- an **all_gather + stacked K×N union** (`merge="gather"`) — the flat
  ``timeWindowAll().reduce`` fan-in, vectorized.

The summary is ``(parent[i32 N], seen[bool N])``; emitted labels are the
minimum vertex slot of each component (canonical), decoded to raw ids for the
final parity oracle (component-set equality, as the reference's test asserts,
``T/example/test/ConnectedComponentsTest.java:40-47``).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.stream import EdgeStream
from ..engine.aggregation import (  # noqa: F401  (threshold re-exported)
    SPARSE_CODEC_MIN_CAPACITY,
    SummaryAggregation,
    sparse_payload_id_check,
)
from ..ops import segments, unionfind
from ..ops.pallas_kernels import on_tpu as pallas_on_tpu


class CCSummary(NamedTuple):
    parent: jax.Array  # i32[N] union-find forest (canonical min-root)
    seen: jax.Array  # bool[N] vertices observed in the stream


# Raw (codec-off) folds switch from the generic union_edges fixpoint to
# the sort-dedup kernel at this chunk size: below it the dedup sorts
# cost more than the rounds they save.
RAW_DEDUP_MIN_CHUNK = 1 << 22


class CCCompactSummary(NamedTuple):
    """Compact-space CC summary (``codec="compact"``): the forest lives in a
    persistent window-scoped compact id space of M slots (M bounds distinct
    touched vertices, not capacity), with the cid → vertex-slot table as the
    decode side."""

    croot: jax.Array  # i32[M] union-find forest over compact ids
    vertex_of: jax.Array  # i32[M] global vertex slot per cid (-1 unassigned)


class CCWindowPane(NamedTuple):
    """One PANE of the windowed compact plan (``windowed=W``): the pane's
    own forest and first-seen decode rows, plus the exact touched-cid
    mask — the window-membership predicate (a self-loop-only vertex
    never moves ``croot`` off the identity, so ``touched`` is recorded
    from the wire payload lanes, not inferred from the forest) and the
    TTL last-seen source."""

    croot: jax.Array  # i32[M] union-find forest over compact ids
    vertex_of: jax.Array  # i32[M] global vertex slot per cid (-1 unassigned)
    touched: jax.Array  # bool[M] cids referenced by this pane's payloads


def _native_ok() -> bool:
    """Is the native chunk combiner available? (Probed once, negative-cached
    in utils.native so a missing toolchain doesn't re-run g++ per chunk.)"""
    from ..utils import native

    return native.available("chunk_combiner")


def cc_labels_numpy(src: np.ndarray, dst: np.ndarray,
                    valid: np.ndarray | None, n_v: int) -> np.ndarray:
    """Pure-numpy fallback for the native chunk combiner: spanning-forest
    labels i32[n_v] of one chunk (-1 for untouched slots)."""
    if valid is not None:
        m = np.asarray(valid, bool)
        src, dst = np.asarray(src)[m], np.asarray(dst)[m]
    lab = np.full((n_v,), -1, np.int32)
    if src.size == 0:
        return lab
    touched = np.zeros((n_v,), bool)
    touched[src] = True
    touched[dst] = True
    lab[touched] = np.nonzero(touched)[0].astype(np.int32)
    while True:
        prev = lab.copy()
        mn = np.minimum(lab[src], lab[dst]).astype(np.int32)
        np.minimum.at(lab, src, mn)
        np.minimum.at(lab, dst, mn)
        t = np.nonzero(touched)[0]
        lab[t] = np.minimum(lab[t], lab[lab[t]])
        if np.array_equal(lab, prev):
            break
    return lab


def cc_pairs_numpy(src: np.ndarray, dst: np.ndarray,
                   valid: np.ndarray | None, n_v: int):
    """Pure-numpy fallback for the native sparse combiner: counted
    (vertex, root) pairs of one chunk's spanning forest — work and payload
    proportional to touched vertices, never ``n_v``."""
    if valid is not None:
        m = np.asarray(valid, bool)
        src, dst = np.asarray(src)[m], np.asarray(dst)[m]
    src = np.asarray(src)
    dst = np.asarray(dst)
    if src.size == 0:
        return np.empty(0, np.int32), np.empty(0, np.int32)
    ids = np.unique(np.concatenate([src, dst]))
    if ids[0] < 0 or ids[-1] >= n_v:
        raise ValueError("cc_pairs_numpy: vertex slot out of range")
    ls = np.searchsorted(ids, src)
    ld = np.searchsorted(ids, dst)
    lab = np.arange(ids.shape[0], dtype=np.int64)
    while True:
        prev = lab
        mn = np.minimum(lab[ls], lab[ld])
        lab = lab.copy()
        np.minimum.at(lab, ls, mn)
        np.minimum.at(lab, ld, mn)
        lab = np.minimum(lab, lab[lab])
        if np.array_equal(lab, prev):
            break
    return ids.astype(np.int32), ids[lab].astype(np.int32)


def merge_chunk_forest(glob: np.ndarray, lab: np.ndarray) -> np.ndarray:
    """Hook a chunk's spanning-forest labels into a global dense forest
    (host numpy — the vectorized CPU analog of the device union).

    Shiloach-Vishkin shape: hook at LABEL (root) indices — writing at the
    vertex indices would lose transitivity when a later chunk lowers part
    of an old component (the old root never learns) — plus one doubling
    step per round until fixpoint. Returns the updated ``glob``.
    """
    ok = lab >= 0
    v = np.nonzero(ok)[0].astype(np.int32)
    r = lab[v]
    while True:
        prev = glob
        lab_u = glob[v]
        lab_v = glob[r]
        lab_lo = np.minimum(lab_u, lab_v)
        lab_hi = np.maximum(lab_u, lab_v)
        glob = glob.copy()
        np.minimum.at(glob, lab_hi, lab_lo)
        glob = np.minimum(glob, glob[glob])
        if np.array_equal(glob, prev):
            break
    return glob


def connected_components_compact(
    vertex_capacity: int, merge: str = "gather",
    compact_capacity: int | None = None, wire: str = "auto",
    unit_block: int = 1 << 18, merge_mode: str = "auto",
    delta_auto_rows: int | None = None,
    windowed: int | None = None, ttl_panes: int | None = None,
) -> SummaryAggregation:
    """CC over a **persistent compact root space** — the large-N fast path
    (``codec="compact"``).

    The ``codec="sparse"`` device fold spent ~85% of each dispatch
    re-compacting pair roots on device (sort + 3 binary-search passes,
    ~1.1s/dispatch at n_v=2^24 on v5e). Here the host ingest codec — which
    already hashes every touched vertex to build the chunk forest — assigns
    each vertex a persistent first-seen compact id
    (:class:`~gelly_tpu.ops.compact_space.CompactIdSession`, one table probe
    per *pair*), and ships pairs already dense in ``[0, M)``. The device
    fold is then a pure M-space union fixpoint: no sort, no searchsorted,
    and **no O(vertex_capacity) work per dispatch** — full-capacity arrays
    are touched exactly once per window, in ``transform``, when the labels
    materialize.

    Same final labels as every other CC plan (canonical min vertex slot per
    component, -1 unseen); same reference semantics
    (``M/SummaryBulkAggregation.java:76-83`` — per-partition partial fold,
    periodic global merge). ``M = compact_capacity`` bounds distinct touched
    vertices per run (NOT edges); overflow raises
    :class:`~gelly_tpu.ops.compact_space.CompactSpaceOverflow` with sizing
    guidance. Requires the ingest codec path: raw-chunk folds (window mode,
    ``ingest_combine=False``) must use ``codec="sparse"`` instead.

    ``wire`` picks the payload wire format (VERDICT r4 items 1+7):

    - ``"segments"`` — the fused native unit codec
      (``native/chunk_combiner.cc:cc_unit_forest_segments``): ONE call
      per merge-window unit runs the dedup-blocked two-level combine and
      emits members grouped by component, each component's root FIRST in
      its segment. The device derives every pair's root-row index as its
      segment start, so the pair wire is 4 bytes/member + one length per
      component — half the ``"pairs"`` bytes — and the per-chunk numpy
      group-combine disappears. ``unit_block`` is the cache-blocking
      granule of the level-1 pass (2^18 edges measured fastest).
    - ``"pairs"`` — the per-chunk sparse combine + (v, root-index) pair
      rows (round 4's format; the no-native-toolchain fallback).
    - ``"auto"`` (default) — segments when the native codec is available.

    ``windowed=W`` builds the PANE-RING variant: the summary type grows
    an exact touched-cid mask (:class:`CCWindowPane`) so the engine's
    ring answers "components over the last W panes" (labels cover only
    window-touched vertices), and the plan exports the persistent-id /
    TTL hooks (``windowed_persist_*``, ``windowed_touched``,
    ``windowed_evict``, ``on_resume_windowed``) the engine's TTL decay
    and exactly-once ring resume ride. ``ttl_panes=T`` (T >= W) arms
    per-vertex decay: a cid slot untouched for T panes is evicted and
    its session capacity reclaimed at the next pane boundary. The
    windowed variant is merge_mode="replicated" only (a pane ring
    retires panes; the dirty-delta merge folds into a carried global —
    exclusive memory models).
    """
    from ..ops.compact_space import CompactIdSession
    from ..utils import native

    n = vertex_capacity
    m = compact_capacity or min(n, 1 << 22)
    session = CompactIdSession(m)
    if wire not in ("auto", "segments", "pairs"):
        raise ValueError(f"wire must be auto/segments/pairs, got {wire}")
    use_segments = wire == "segments" or (
        wire == "auto" and native.unit_segments_available()
    )

    def init() -> CCCompactSummary:
        return CCCompactSummary(
            croot=unionfind.fresh_forest(m),
            vertex_of=jnp.full((m,), -1, jnp.int32),
        )

    def fold(s, chunk):
        raise NotImplementedError(
            "codec='compact' folds compressed payloads only (its id space "
            "is assigned by the host ingest codec); use codec='sparse' for "
            "raw-chunk or window_ms plans"
        )

    def host_compress(chunk) -> dict:
        if native.sparse_codecs_available():
            v, r = native.cc_chunk_combine_sparse(
                np.asarray(chunk.src), np.asarray(chunk.dst),
                np.asarray(chunk.valid), n,
            )
        else:
            v, r = cc_pairs_numpy(chunk.src, chunk.dst, chunk.valid, n)
        return {"v": v, "r": r}

    def host_compress_raw(chunk) -> dict:
        # Segment wire: per-chunk compression is a no-op (zero-copy views)
        # — the WHOLE unit combines in one fused native call in the
        # stacker, where blocking keeps the intern tables cache-resident
        # regardless of the caller's chunk size.
        return {
            "src": np.asarray(chunk.src),
            "dst": np.asarray(chunk.dst),
            "valid": np.asarray(chunk.valid),
        }

    def _combine_pairs_idx(av: np.ndarray, ar: np.ndarray):
        """Merge a group's pairs into one forest, with each pair's root
        reported as its INDEX in the output (wire format of the star fold:
        the device resolves root labels by indexing its own chased array,
        saving a second pointer chase per pair)."""
        if native.sparse_idx_available():
            return native.cc_chunk_combine_sparse_idx(av, ar, None, n)
        v, r = cc_pairs_numpy(av, ar, None, n)
        return v, r, np.searchsorted(v, r).astype(np.int32)

    def stack_compact(payloads: list, groups: int = 1,
                      seq: int | None = None) -> dict:
        from ..engine.aggregation import bucket_stack_payloads

        # Stateless group combine first — concurrent stagers keep this
        # (the heavyweight step) parallel.
        size = -(-max(len(payloads), 1) // groups)
        combined = [
            _combine_pairs_idx(
                np.concatenate([q["v"] for q in payloads[i:i + size]]),
                np.concatenate([q["r"] for q in payloads[i:i + size]]),
            )
            for i in range(0, len(payloads), size)
        ]
        # Stateful cid assignment in STREAM order (see CompactIdSession:
        # a unit folded first must carry the first-seen records).
        if seq is not None:
            session.await_turn(seq)
        try:
            rows = []
            for v2, _, ri2 in combined:
                # Persistent cid assignment at pair rate; the root side
                # travels as a row index, so only ``v`` needs the mapping.
                cv, new_ids, base = session.assign(v2)
                rows.append({
                    "v": cv, "ri": ri2, "newv": new_ids,
                    "base": np.asarray(base, np.int32),
                })
            while len(rows) < groups:
                rows.append({
                    "v": np.empty(0, np.int32), "ri": np.empty(0, np.int32),
                    "newv": np.empty(0, np.int32),
                    "base": np.asarray(session.assigned, np.int32),
                })
        finally:
            if seq is not None:
                session.complete_turn(seq)
        # Quantum (not pow-2) buckets: the star fold's gather cost scales
        # with padded lanes, so at multi-M pair counts a pow-2 ladder
        # would waste up to 2x device work for compile-cache stability the
        # coarse quantum already provides. Both the quantum and the floor
        # cap at m: a row can never exceed the compact capacity, so
        # small-M plans must not pad to the large-M granule.
        return bucket_stack_payloads(
            rows, {"v": -1, "ri": 0, "newv": -1},
            min_bucket=min(1024, m), quantum=min(1 << 18, m),
        )

    def stack_segments(payloads: list, groups: int = 1,
                       seq: int | None = None) -> dict:
        from ..engine.aggregation import bucket_stack_payloads

        # Fused unit combine (stateless, heavy): ONE native call per
        # mesh-shard subgroup over the subgroup's concatenated raw edges
        # — dedup-blocked two-level union-find emitting root-first
        # segments in VERTEX space (cc_unit_forest_segments).
        size = -(-max(len(payloads), 1) // groups)
        combined = []
        for i in range(0, len(payloads), size):
            builder = native.UnitForestBuilder(n, block=unit_block)
            for p in payloads[i:i + size]:
                va = np.asarray(p["valid"])
                builder.add(
                    p["src"], p["dst"], None if bool(va.all()) else va
                )
            combined.append(builder.finish())
        # Stateful cid remap in STREAM order (one session probe pass per
        # member; order-preserving, so the segment structure carries
        # over to cid space unchanged).
        if seq is not None:
            session.await_turn(seq)
        try:
            rows = []
            for mv, ln in combined:
                cids, new_ids, base = session.assign(mv)
                rows.append({
                    "m": cids, "len": ln, "newv": new_ids,
                    "base": np.asarray(base, np.int32),
                })
            while len(rows) < groups:
                rows.append({
                    "m": np.empty(0, np.int32),
                    "len": np.empty(0, np.int32),
                    "newv": np.empty(0, np.int32),
                    "base": np.asarray(session.assigned, np.int32),
                })
        finally:
            if seq is not None:
                session.complete_turn(seq)
        # Per-key buckets: lengths (∝ components) and newv (∝ FRESH
        # vertices) run far below members (∝ touched vertices) — giving
        # each its own quantum ladder instead of the members' bucket was
        # measured as ~1/3 of the wire bytes at Twitter scale.
        return bucket_stack_payloads(
            rows, {"m": -1, "len": 0, "newv": -1},
            min_bucket=min(1024, m), quantum=min(1 << 18, m),
            per_key={
                "len": (min(1024, m), min(1 << 13, m)),
                "newv": (min(1024, m), min(1 << 16, m)),
            },
        )

    def _append_vertex_of(s: CCCompactSummary, payload) -> jax.Array:
        # Shared decode-table append: rows carry their own base, so
        # staging order never has to match fold order.
        newv = jnp.atleast_2d(payload["newv"])  # global slots of fresh cids
        base = payload["base"].reshape(-1)  # first cid of each fresh block
        k, cap = newv.shape
        pos = base[:, None] + jnp.arange(cap, dtype=jnp.int32)[None, :]
        okn = newv >= 0
        return s.vertex_of.at[
            jnp.where(okn, pos, m).reshape(-1)
        ].set(jnp.where(okn, newv, 0).reshape(-1), mode="drop")

    def fold_compressed(s: CCCompactSummary, payload) -> CCCompactSummary:
        # Leaves arrive [K, cap] from the engine's stacked dispatch, or
        # [cap] when a scan strips the batch axis (the device-bound bench).
        vertex_of = _append_vertex_of(s, payload)
        v = jnp.atleast_2d(payload["v"])
        ri = jnp.atleast_2d(payload["ri"])  # row-local root indices
        kb, capb = v.shape
        ri_flat = (
            ri + capb * jnp.arange(kb, dtype=jnp.int32)[:, None]
        ).reshape(-1)
        v = v.reshape(-1)
        croot = unionfind.union_pairs_star(s.croot, v, ri_flat, v >= 0)
        return CCCompactSummary(croot, vertex_of)

    def fold_segments(s: CCCompactSummary, payload) -> CCCompactSummary:
        # Segment wire: members [K, capm] grouped by component, each
        # component's root FIRST in its segment; lengths [K, capr]. The
        # root-row index of every member lane is its segment START —
        # derived on device from the lengths' cumsum, replacing the
        # shipped per-pair ri (half the pair bytes on the H2D link).
        vertex_of = _append_vertex_of(s, payload)
        mm = jnp.atleast_2d(payload["m"])
        ln = jnp.atleast_2d(payload["len"])
        kb, capm = mm.shape
        cum = jnp.cumsum(ln, axis=1)
        total = cum[:, -1]
        lane = jnp.arange(capm, dtype=jnp.int32)
        # Segment of each lane = # cum entries <= lane (searchsorted
        # right); clamp covers padding lanes past the last segment.
        seg = jax.vmap(
            lambda c: jnp.searchsorted(c, lane, side="right")
        )(cum).astype(jnp.int32)
        seg = jnp.minimum(seg, ln.shape[1] - 1)
        starts = (cum - ln).astype(jnp.int32)
        ri = jnp.take_along_axis(starts, seg, axis=1)
        valid = lane[None, :] < total[:, None]
        ri_flat = (
            ri + capm * jnp.arange(kb, dtype=jnp.int32)[:, None]
        ).reshape(-1)
        croot = unionfind.union_pairs_star(
            s.croot, mm.reshape(-1), ri_flat, valid.reshape(-1)
        )
        return CCCompactSummary(croot, vertex_of)

    def combine(a: CCCompactSummary, b: CCCompactSummary) -> CCCompactSummary:
        return CCCompactSummary(
            croot=unionfind.merge_forests(a.croot, b.croot),
            # Each cid's vertex is recorded by exactly one payload row;
            # -1 elsewhere, so elementwise max merges the decode tables.
            vertex_of=jnp.maximum(a.vertex_of, b.vertex_of),
        )

    def merge_stacked(st: CCCompactSummary) -> CCCompactSummary:
        return CCCompactSummary(
            croot=unionfind.merge_forest_stack(st.croot),
            vertex_of=jnp.max(st.vertex_of, axis=0),
        )

    def merge_dirty_count(local: CCCompactSummary) -> jax.Array:
        # A window's locals touch a cid either by assigning its decode
        # entry (fresh cids: vertex_of >= 0) or by hooking its root (cids
        # from earlier windows: croot moved off the identity).
        dirty = (local.vertex_of >= 0) | (
            local.croot != jnp.arange(m, dtype=jnp.int32)
        )
        return jnp.sum(dirty.astype(jnp.int32))

    def merge_delta(base: CCCompactSummary, local: CCCompactSummary,
                    bucket: int) -> CCCompactSummary:
        # Dirty-delta mesh merge in cid space: gather (cid, croot,
        # vertex_of) rows for the window's touched cids only. croot rows
        # are union edges (same argument as the CCSummary delta); each
        # cid's vertex is recorded by exactly one row globally, so the
        # max-scatter reproduces the elementwise-max decode-table merge.
        from ..parallel import collectives

        dirty = (local.vertex_of >= 0) | (
            local.croot != jnp.arange(m, dtype=jnp.int32)
        )
        slots, vals, _ = collectives.compact_delta(
            dirty, {"r": local.croot, "v": local.vertex_of}, bucket
        )
        gs, gv = collectives.gather_delta(slots, vals)
        ok = gs >= 0
        si = jnp.where(ok, gs, 0)
        ri = jnp.where(ok, gv["r"], 0)
        # Rows-proportional apply (see _cc_merge_delta): no full-capacity
        # flatten; transform's pointer_jump chases through the depth.
        croot = unionfind.union_pairs_rooted(base.croot, si, ri, ok)
        vertex_of = base.vertex_of.at[jnp.where(ok, gs, m)].max(
            jnp.where(ok, gv["v"], -1), mode="drop"
        )
        return CCCompactSummary(croot, vertex_of)

    def transform(s: CCCompactSummary) -> jax.Array:
        # The ONLY full-capacity op in the plan: materialize i32[n] labels
        # once per window close.
        root = unionfind.pointer_jump(s.croot)
        ok = s.vertex_of >= 0
        canon = jnp.full((m,), segments.INT_MAX, jnp.int32).at[
            jnp.where(ok, root, m)
        ].min(jnp.where(ok, s.vertex_of, segments.INT_MAX), mode="drop")
        lab_c = canon[root]
        return jnp.full((n,), -1, jnp.int32).at[
            jnp.where(ok, s.vertex_of, n)
        ].set(jnp.where(ok, lab_c, -1), mode="drop")

    def flatten(s: CCCompactSummary) -> CCCompactSummary:
        # Cadenced path flatten: the star/rooted pair folds skip the
        # global flatten per dispatch (their documented contract), so
        # croot chase depth grows on long streams; one pointer_jump at
        # checkpoint cadence bounds it. vertex_of is depth-free.
        return CCCompactSummary(
            unionfind.pointer_jump(s.croot), s.vertex_of
        )

    if windowed is not None:
        return _windowed_compact_variant(
            windowed, ttl_panes, m, n, session,
            init=init, fold=fold, combine=combine, transform=transform,
            merge_stacked=merge_stacked if merge == "gather" else None,
            host_compress=(
                host_compress_raw if use_segments else host_compress
            ),
            fold_compressed=(
                fold_segments if use_segments else fold_compressed
            ),
            stack_payloads=(
                stack_segments if use_segments else stack_compact
            ),
            member_key="m" if use_segments else "v",
        )
    if ttl_panes is not None:
        raise ValueError(
            "ttl_panes requires windowed=W (TTL stamps are last-seen "
            "PANE indices; there is no pane clock without a ring)"
        )
    agg = SummaryAggregation(
        init=init,
        fold=fold,
        combine=combine,
        transform=transform,
        merge_stacked=merge_stacked if merge == "gather" else None,
        transient=False,
        host_compress=host_compress_raw if use_segments else host_compress,
        fold_compressed=fold_segments if use_segments else fold_compressed,
        stack_payloads=stack_segments if use_segments else stack_compact,
        fold_accumulates=True,
        flatten=flatten,
        requires_codec=True,
        stack_ordered=True,
        on_stage_error=session.complete_turn,
        on_run_start=session.reset,
        ordered_wait_s=lambda: session.wait_s,
        on_resume=lambda summary: session.rebuild_from_vertex_of(
            np.asarray(summary.vertex_of)
        ),
        merge_mode=resolve_merge_mode(merge_mode),
        merge_delta=merge_delta,
        merge_dirty_count=merge_dirty_count,
        merge_delta_auto_rows=(
            m // 4 if delta_auto_rows is None else int(delta_auto_rows)
        ),
        name="connected-components-compact",
    )
    agg.session = session
    agg.compact_capacity = m
    return agg


def _windowed_compact_variant(
    windowed: int, ttl_panes: int | None, m: int, n: int, session,
    *, init, fold, combine, transform, merge_stacked, host_compress,
    fold_compressed, stack_payloads, member_key: str,
) -> SummaryAggregation:
    """Assemble the pane-ring compact plan: wrap the base compact fold /
    combine / transform in :class:`CCWindowPane` (an exact touched-cid
    mask rides every pane) and attach the engine's windowed hooks.

    The touched mask is recorded from the WIRE payload's member lanes
    (``v`` on the pairs wire, ``m`` on the segments wire; padding lanes
    are -1), not inferred from the forest — a self-loop-only vertex
    never moves ``croot`` off the identity, yet it IS in the window.

    ``windowed_evict`` (the TTL hook): survivors are renumbered
    order-preserving onto a dense cid prefix, every live pane's leaves
    are gathered through the renumbering, and the session is rebuilt
    from the compacted persistent map — so ``session.assigned`` drops
    back to the live-slot count and the freed capacity is reusable.
    Sound because T >= W (engine-enforced): an evicted cid is untouched
    in every live pane, so its rows are identity/-1/False everywhere
    and no surviving cid's ``croot`` can point at it (a union would
    have stamped it touched).
    """
    if windowed < 1:
        raise ValueError(f"windowed must be >= 1 pane, got {windowed}")
    if ttl_panes is not None and ttl_panes < windowed:
        raise ValueError(
            f"ttl_panes={ttl_panes} < windowed={windowed}: a slot must "
            "outlive the ring (T >= W) so eviction never rewrites a "
            "pane that still references it"
        )

    def init_pane() -> CCWindowPane:
        s = init()
        return CCWindowPane(s.croot, s.vertex_of, jnp.zeros((m,), bool))

    def fold_pane(s: CCWindowPane, payload) -> CCWindowPane:
        base = fold_compressed(
            CCCompactSummary(s.croot, s.vertex_of), payload
        )
        mem = jnp.atleast_2d(payload[member_key]).reshape(-1)
        touched = s.touched.at[jnp.where(mem >= 0, mem, m)].set(
            True, mode="drop"
        )
        return CCWindowPane(base.croot, base.vertex_of, touched)

    def combine_pane(a: CCWindowPane, b: CCWindowPane) -> CCWindowPane:
        c = combine(
            CCCompactSummary(a.croot, a.vertex_of),
            CCCompactSummary(b.croot, b.vertex_of),
        )
        return CCWindowPane(c.croot, c.vertex_of, a.touched | b.touched)

    def merge_stacked_pane(st: CCWindowPane) -> CCWindowPane:
        c = merge_stacked(CCCompactSummary(st.croot, st.vertex_of))
        return CCWindowPane(
            c.croot, c.vertex_of, jnp.any(st.touched, axis=0)
        )

    def transform_pane(s: CCWindowPane) -> jax.Array:
        # Same shape as the base transform, with the WINDOW-membership
        # predicate: labels cover touched cids only (the engine
        # substitutes the persistent vertex_of before this runs, so
        # every touched cid decodes).
        root = unionfind.pointer_jump(s.croot)
        ok = s.touched & (s.vertex_of >= 0)
        canon = jnp.full((m,), segments.INT_MAX, jnp.int32).at[
            jnp.where(ok, root, m)
        ].min(jnp.where(ok, s.vertex_of, segments.INT_MAX), mode="drop")
        lab_c = canon[root]
        return jnp.full((n,), -1, jnp.int32).at[
            jnp.where(ok, s.vertex_of, n)
        ].set(jnp.where(ok, lab_c, -1), mode="drop")

    def flatten_pane(s: CCWindowPane) -> CCWindowPane:
        return CCWindowPane(
            unionfind.pointer_jump(s.croot), s.vertex_of, s.touched
        )

    def windowed_evict(panes, persist, stale):
        # Host-side, called by the engine at a pane boundary with the
        # pipeline quiesced (prefetch_depth=0 / h2d_depth=0 — no
        # staged-but-unfolded payloads carry the old cids).
        assigned = session.assigned
        surv = np.flatnonzero(~np.asarray(stale)[:assigned])
        k = surv.shape[0]
        perm = np.full((m,), -1, np.int32)
        perm[surv] = np.arange(k, dtype=np.int32)
        out = []
        for p in panes:
            croot = np.arange(m, dtype=np.int32)
            croot[:k] = perm[np.asarray(p.croot)[surv]]
            vof = np.full((m,), -1, np.int32)
            vof[:k] = np.asarray(p.vertex_of)[surv]
            tch = np.zeros((m,), bool)
            tch[:k] = np.asarray(p.touched)[surv]
            out.append(CCWindowPane(croot, vof, tch))
        p2 = np.full((m,), -1, np.int32)
        p2[:k] = np.asarray(persist)[surv]
        session.rebuild_from_vertex_of(p2)
        return out, p2, surv

    agg = SummaryAggregation(
        init=init_pane,
        fold=fold,
        combine=combine_pane,
        transform=transform_pane,
        merge_stacked=(
            merge_stacked_pane if merge_stacked is not None else None
        ),
        transient=False,
        host_compress=host_compress,
        fold_compressed=fold_pane,
        stack_payloads=stack_payloads,
        fold_accumulates=True,
        flatten=flatten_pane,
        requires_codec=True,
        stack_ordered=True,
        on_stage_error=session.complete_turn,
        on_run_start=session.reset,
        ordered_wait_s=lambda: session.wait_s,
        merge_mode="replicated",
        name="connected-components-compact-windowed",
    )
    agg.session = session
    agg.compact_capacity = m
    agg.windowed_panes = int(windowed)
    if ttl_panes is not None:
        agg.windowed_ttl_panes = int(ttl_panes)
    agg.windowed_persist_init = lambda: jnp.full((m,), -1, jnp.int32)
    agg.windowed_persist_update = jax.jit(
        lambda p, pane: jnp.maximum(p, pane.vertex_of)
    )
    agg.windowed_query_fixup = lambda q, persist: q._replace(
        vertex_of=persist
    )
    agg.windowed_touched = lambda pane: pane.touched
    agg.windowed_evict = windowed_evict
    agg.on_resume_windowed = lambda persist: session.rebuild_from_vertex_of(
        np.asarray(persist)
    )
    return agg


def resolve_merge_mode(merge_mode: str) -> str:
    """Shared ``merge_mode=`` knob semantics for the cross-shard window
    merge: validate ``"auto"``/``"delta"``/``"replicated"``.

    - ``"replicated"`` — the full-summary merge (butterfly / hierarchical
      tree / gather+stacked union): cost ∝ capacity per window, the
      BENCH_r05 ``sharded_state_cc`` wall (0.58s → 32.2s from 1M → 16M
      slots at a fixed pair count).
    - ``"delta"`` — all_gather only the dirty ``(slot, parent)`` entries
      the window's folds marked and union them into the carried global
      summary: merge cost ∝ hooks-since-last-merge.
    - ``"auto"`` — per-window measured decision: the engine counts the
      dirty entries (one scalar D2H per window close) and takes the delta
      path while the gathered rows stay under the plan's
      ``merge_delta_auto_rows`` bound, falling back to the replicated
      merge (the plan's configured tree — hierarchical when
      ``merge_degree`` is set) on dense windows.
    """
    if merge_mode not in ("auto", "delta", "replicated"):
        raise ValueError(
            f"merge_mode must be auto/delta/replicated, got {merge_mode!r}"
        )
    return merge_mode


def _cc_merge_delta(n: int):
    """Build the CCSummary dirty-delta merge (runs per-shard inside
    ``shard_map``): compact this shard's touched ``(slot, parent)``
    entries, all_gather every shard's rows, and union them into the
    replicated base summary. Exact: a fresh-forest local summary IS its
    edge set ``{(i, parent[i])}`` plus the seen marks, so applying the
    gathered pairs to the base is the same merge ``merge_forest_stack``
    computes — minus the ``S × capacity`` traffic."""
    from ..parallel import collectives

    def merge_dirty_count(local: CCSummary) -> jax.Array:
        dirty = local.seen | (
            local.parent != jnp.arange(n, dtype=jnp.int32)
        )
        return jnp.sum(dirty.astype(jnp.int32))

    def merge_delta(base: CCSummary, local: CCSummary,
                    bucket: int) -> CCSummary:
        dirty = local.seen | (
            local.parent != jnp.arange(n, dtype=jnp.int32)
        )
        slots, vals, _ = collectives.compact_delta(
            dirty, local.parent, bucket
        )
        gs, gv = collectives.gather_delta(slots, vals)
        ok = gs >= 0
        si = jnp.where(ok, gs, 0)
        vi = jnp.where(ok, gv, 0)
        # union_pairs_rooted: EVERY per-round op is sized to the gathered
        # rows (pair-sized chases + one scatter-min), and no full-capacity
        # flatten — the whole point of the delta merge. Depth grows O(1)
        # per window; the transform's label chase and later merges chase
        # through it (their documented contract).
        parent = unionfind.union_pairs_rooted(base.parent, si, vi, ok)
        seen = base.seen.at[jnp.where(ok, gs, n)].set(True, mode="drop")
        return CCSummary(parent, seen)

    return merge_delta, merge_dirty_count


def resolve_fold_backend(fold_backend: str, vertex_capacity: int) -> str:
    """Shared ``fold_backend=`` knob semantics: validate and resolve
    ``"auto"``/``"xla"``/``"pallas"`` for the raw device fold.

    ``"auto"`` resolves to ``"xla"``: the Pallas path's profitability is
    hardware-dependent (it trades MXU flops for HBM random-touch latency;
    see the bench's ``gather_study`` block), so the measured sweep — not
    a heuristic — should flip the default. ``"pallas"`` validates the
    capacity against the kernel's window-blocking requirements up front,
    at plan-build time, instead of failing mid-stream.
    """
    if fold_backend not in ("auto", "xla", "pallas"):
        raise ValueError(
            f"fold_backend must be auto/xla/pallas, got {fold_backend!r}"
        )
    if fold_backend == "pallas":
        from ..ops.pallas_kernels import gatherable

        if not gatherable(vertex_capacity):
            raise ValueError(
                f"fold_backend='pallas' needs a window-blockable vertex "
                f"capacity (multiple of 128 lanes spanning >= 2 windows, "
                f"<= 2^24); got {vertex_capacity}"
            )
        return "pallas"
    return "xla"


def cc_tenant_tier(
    vertex_capacity: int, chunk_capacity: int = 1 << 10,
    fold_backend: str = "auto", delta_auto_rows: int | None = None,
    compressed: bool = False, codec: str = "auto",
) -> tuple[SummaryAggregation, int]:
    """Build a CC plan suitable for one multi-tenant capacity tier
    (``engine/tenants.py``) — returns ``(agg, chunk_capacity)`` for
    ``MultiTenantEngine.add_tier``.

    ``compressed=False`` (default) builds the raw-fold tier: the
    stacked batch vmaps ``fold`` over raw per-tenant chunks.
    ``compressed=True`` keeps the stateless ingest codec ON, for a
    ``add_tier(..., compressed=True)`` tier whose lanes fold
    PRE-COMPRESSED payloads (compressed once at the producer — the
    submitter or a wire client; ``codec`` picks the payload format,
    ``"sparse"`` being the wire-win shape). The stateful compact-id
    codec (``codec="compact"``) stays unusable either way: its
    id-assignment session consumes payloads in global stream order,
    which concurrent tenant lanes cannot provide. ``vertex_capacity``
    is the tier's capacity class: all tenants of the tier share one
    compiled program per lane width, so admit tenants into the
    smallest tier whose capacity covers them.
    """
    agg = connected_components(
        vertex_capacity, merge="gather", ingest_combine=compressed,
        codec=codec,
        fold_backend=fold_backend, delta_auto_rows=delta_auto_rows,
    )
    return agg, int(chunk_capacity)


def connected_components(
    vertex_capacity: int, merge: str = "tree", ingest_combine: bool = True,
    codec: str = "auto", compact_capacity: int | None = None,
    fold_backend: str = "auto", merge_mode: str = "auto",
    delta_auto_rows: int | None = None,
    windowed: int | None = None, ttl_panes: int | None = None,
) -> SummaryAggregation:
    """Build the CC aggregation over a slot space of ``vertex_capacity``.

    ``merge="tree"`` → butterfly merge-tree (ConnectedComponentsTree);
    ``merge="gather"`` → all_gather + stacked union (flat bulk aggregation).

    ``ingest_combine`` (default on) attaches the ingest codec: each chunk is
    pre-reduced on the host to its spanning forest (the reference's
    per-partition partial fold, M/SummaryBulkAggregation.java:76-80, moved
    to the ingest side). The device then unions the (vertex, root) star
    edges, preserving connectivity exactly — 1-2 orders of magnitude fewer
    H2D bytes per edge.

    ``codec`` picks the payload wire format:

    - ``"dense"`` — i32[n_v] label array per chunk. Optimal when the slot
      space is small relative to chunk size (payload is a fixed n_v*4
      bytes and the device fold is a fixed-shape star union).
    - ``"sparse"`` — counted (vertex, root) pairs, bucket-padded per batch
      (:func:`~gelly_tpu.engine.aggregation.bucket_stack_payloads`).
      Payload ∝ touched vertices — required at Twitter-class n_v, where a
      dense payload (e.g. 64 MB at n_v = 2^24) would invert the codec's
      compression. Host combine cost is O(chunk), not O(n_v), matching
      the reference's touched-keys-proportional partial fold
      (M/SummaryBulkAggregation.java:109-130).
    - ``"compact"`` — persistent compact root space
      (:func:`connected_components_compact`): the host codec assigns
      window-scoped compact ids and the device folds in an M-slot space,
      with zero per-dispatch O(capacity) work. The large-N throughput
      plan; requires the ingest codec (no raw-chunk/window_ms fold).
    - ``"auto"`` (default) — sparse iff ``vertex_capacity >=``
      :data:`SPARSE_CODEC_MIN_CAPACITY` (2^20).

    ``merge_mode`` picks the cross-shard window merge
    (:func:`resolve_merge_mode`): ``"delta"`` gathers only the window's
    dirty ``(slot, parent)`` entries (merge ∝ hooks, not capacity),
    ``"replicated"`` keeps the full-summary merge, ``"auto"`` (default)
    measures the dirty count each window close and picks per window.
    Like ``fold_backend``, the engine's compiled-plan cache keys on it.

    ``delta_auto_rows`` overrides the ``"auto"`` crossover bound (max
    gathered delta rows before the replicated merge wins). Default is
    the ``capacity / 4`` structural guess; the bench's
    ``merge_delta_crossover`` block measures the real crossover per
    chip against the ``engine.window_dirty_rows`` gauge — pass the
    calibrated value here (``BENCH_tenants_r01.json`` records one for
    the CPU mesh).

    ``fold_backend`` picks the RAW device fold's kernel backend
    (:func:`resolve_fold_backend`): ``"pallas"`` routes the large-chunk
    sort-dedup fold's sorted chases through the VMEM-blocked gather
    kernel (:func:`~gelly_tpu.ops.pallas_kernels.sorted_window_gather`,
    exact — window misses fall through to the exact tail fixpoint);
    ``"auto"`` stays on XLA until the recorded bench sweep says
    otherwise. The codec plans' device folds are pair/star folds that
    never run the raw dedup kernel, so the knob only shapes the
    codec-off fold path (window mode, ``ingest_combine=False``, and the
    device-bound bench).

    ``windowed=W`` marks the plan for the engine's sliding pane ring
    (``run_aggregation(windowed=...)``): emissions cover the last W
    merge windows instead of the whole stream, at O(1) amortized
    combines per pane close. Forces ``merge_mode="replicated"`` (the
    dirty-delta merge folds into a carried global — incompatible with
    pane retirement). ``ttl_panes=T`` (per-vertex decay) additionally
    needs ``codec="compact"`` — only the compact-id session has an
    eviction hook.
    """
    from ..engine.aggregation import resolve_sparse_codec

    if codec == "compact":
        if not ingest_combine:
            raise ValueError("codec='compact' requires ingest_combine=True")
        return connected_components_compact(
            vertex_capacity, merge=merge, compact_capacity=compact_capacity,
            merge_mode=merge_mode, delta_auto_rows=delta_auto_rows,
            windowed=windowed, ttl_panes=ttl_panes,
        )
    if ttl_panes is not None:
        raise ValueError(
            "ttl_panes needs the compact-id plan (codec='compact'): "
            "per-vertex decay evicts through the CompactIdSession "
            "rebuild hook, which dense/sparse plans have no analog of"
        )
    if windowed is not None:
        if int(windowed) < 1:
            raise ValueError(
                f"windowed must be >= 1 pane, got {windowed}"
            )
        # A pane ring retires panes, so the dirty-delta merge (which
        # folds into a CARRIED global summary) cannot engage — the
        # windowed variant is replicated-merge only, and CCSummary
        # needs no other change: `seen` already gives the window-
        # membership predicate once panes fold from fresh locals.
        merge_mode = "replicated"
    n = vertex_capacity
    sparse = resolve_sparse_codec(codec, n)
    backend = resolve_fold_backend(fold_backend, n)
    mode = resolve_merge_mode(merge_mode)
    # Static per-plan choice: jit specializes the fold on it, and the
    # engine's compiled-plan cache keys on agg.fold_backend.
    interp = None if backend == "xla" else not pallas_on_tpu()

    def init() -> CCSummary:
        return CCSummary(
            parent=unionfind.fresh_forest(n), seen=jnp.zeros((n,), bool)
        )

    def fold(s: CCSummary, chunk) -> CCSummary:
        if chunk.capacity >= RAW_DEDUP_MIN_CHUNK:
            # Large-chunk raw path: sort-dedup + verified hook rounds +
            # compacted exact tail (union_edges_dedup) — ~10x the generic
            # fixpoint at Twitter-scale capacity (its O(capacity) random
            # doubling per round was the measured cost). Caps are perf
            # knobs only; overflow falls back to the exact fixpoint.
            parent = unionfind.union_edges_dedup(
                s.parent, chunk.src, chunk.dst, chunk.valid,
                # 3/16 of the chunk covers the distinct-pair counts of
                # power-law streams with ~1.4x margin (2^25-edge Zipf
                # chunks measure ~13% distinct); fixpoint op cost scales
                # with this cap, and overflow only costs speed (exact
                # full-width fallback), never correctness.
                unique_cap=max(1 << 20, 3 * (chunk.capacity >> 4)),
                backend=backend, interpret=interp,
            )
        else:
            parent = unionfind.union_edges(
                s.parent, chunk.src, chunk.dst, chunk.valid
            )
        seen = segments.mark_seen(s.seen, chunk.src, chunk.valid)
        seen = segments.mark_seen(seen, chunk.dst, chunk.valid)
        return CCSummary(parent, seen)

    def host_compress(chunk) -> np.ndarray:
        if _native_ok():
            from ..utils.native import cc_chunk_combine

            return cc_chunk_combine(
                np.asarray(chunk.src), np.asarray(chunk.dst),
                np.asarray(chunk.valid), n,
            )
        return cc_labels_numpy(chunk.src, chunk.dst, chunk.valid, n)

    def fold_compressed(s: CCSummary, labels: jax.Array) -> CCSummary:
        # labels: i32[K, n] — K chunk forests. Every (v, labels[k, v] >= 0)
        # pair is a union edge; one joint fixpoint unions all K at once
        # (cheaper than K sequential fixpoints — the star edges from
        # different chunks hook through each other in the same rounds).
        k = labels.shape[0]
        present = jnp.any(labels >= 0, axis=0)
        v = jnp.broadcast_to(
            jnp.arange(n, dtype=jnp.int32), (k, n)
        ).reshape(-1)
        lab = labels.reshape(-1)
        ok = lab >= 0
        parent = unionfind.union_edges(
            s.parent, v, jnp.where(ok, lab, 0).astype(jnp.int32), ok
        )
        return CCSummary(parent, s.seen | present)

    def host_compress_sparse(chunk) -> dict:
        from ..utils import native

        if native.sparse_codecs_available():
            v, r = native.cc_chunk_combine_sparse(
                np.asarray(chunk.src), np.asarray(chunk.dst),
                np.asarray(chunk.valid), n,
            )
        else:
            v, r = cc_pairs_numpy(chunk.src, chunk.dst, chunk.valid, n)
        return {"v": v, "r": r}

    def _combine_pairs(av: np.ndarray, ar: np.ndarray):
        # Pairs are union edges: one more sparse-combiner pass merges a
        # whole group's chunk forests into one (the SummaryTreeReduce
        # partial-merge level run on the ingest side).
        from ..utils import native

        if native.sparse_codecs_available():
            return native.cc_chunk_combine_sparse(av, ar, None, n)
        return cc_pairs_numpy(av, ar, None, n)

    def stack_sparse(payloads: list, groups: int = 1) -> dict:
        from ..engine.aggregation import (
            bucket_stack_payloads,
            group_combine_payloads,
        )

        payloads = group_combine_payloads(
            payloads, groups,
            lambda grp: dict(zip(("v", "r"), _combine_pairs(
                np.concatenate([q["v"] for q in grp]),
                np.concatenate([q["r"] for q in grp]),
            ))),
            {"v": np.empty(0, np.int32), "r": np.empty(0, np.int32)},
        )
        return bucket_stack_payloads(payloads, {"v": -1, "r": 0})

    def fold_compressed_sparse(s: CCSummary, payload) -> CCSummary:
        # payload: {"v": i32[K, cap], "r": i32[K, cap]} — K chunks' counted
        # (vertex, root) pairs, -1-padded. The pairs are union edges; one
        # joint fixpoint unions all K chunks at once, in a compacted root
        # space (touched slots << vertex_capacity is exactly the sparse
        # codec's regime — union_pairs_compact keeps per-round work ∝
        # pairs, not capacity).
        v = payload["v"].reshape(-1)
        r = payload["r"].reshape(-1)
        ok = v >= 0
        vi = jnp.where(ok, v, 0)
        if 4 * v.size <= n:
            # Compacted-root-space union: per-round work ∝ pairs. Only a
            # win while the 2L local space is comfortably below the
            # capacity the generic fixpoint would walk per round (shapes
            # are static, so this resolves at trace time).
            parent = unionfind.union_pairs_compact(s.parent, vi, r, ok)
        else:
            parent = unionfind.union_edges(s.parent, vi, r, ok)
        seen = segments.mark_seen(s.seen, vi, ok)
        return CCSummary(parent, seen)

    def combine(a: CCSummary, b: CCSummary) -> CCSummary:
        return CCSummary(
            parent=unionfind.merge_forests(a.parent, b.parent),
            seen=a.seen | b.seen,
        )

    def merge_stacked(st: CCSummary) -> CCSummary:
        return CCSummary(
            parent=unionfind.merge_forest_stack(st.parent),
            seen=jnp.any(st.seen, axis=0),
        )

    def transform(s: CCSummary) -> jax.Array:
        return unionfind.component_labels(s.parent, s.seen)

    def flatten(s: CCSummary) -> CCSummary:
        # Cadenced path flatten (engine runs it at checkpoint cadence):
        # the delta merge's union_pairs_rooted grows chase depth O(1)
        # per window; one full pointer_jump here keeps depth <= 1 across
        # arbitrarily long streams. Labels are unchanged — pointer_jump
        # only shortcuts chains to the same roots.
        return CCSummary(unionfind.pointer_jump(s.parent), s.seen)

    _mk_delta, _mk_count = _cc_merge_delta(n)
    if windowed is not None:
        _mk_delta = _mk_count = None

    agg = SummaryAggregation(
        init=init,
        fold=fold,
        combine=combine,
        transform=transform,
        merge_stacked=merge_stacked if merge == "gather" else None,
        transient=False,
        host_compress=(
            (host_compress_sparse if sparse else host_compress)
            if ingest_combine else None
        ),
        fold_compressed=(
            (fold_compressed_sparse if sparse else fold_compressed)
            if ingest_combine else None
        ),
        stack_payloads=(
            stack_sparse if (ingest_combine and sparse) else None
        ),
        # Wire pad values of the sparse pair payload (consumers that
        # stack per-chunk payloads themselves — the tenant engine's
        # compressed tiers — pad with these; -1 lanes fold as no-ops),
        # and the producer-payload id range check (wire-ingest parity:
        # out-of-range ids raise at staging, never silently clamp).
        codec_pad_values=(
            {"v": -1, "r": 0} if (ingest_combine and sparse) else None
        ),
        codec_payload_check=(
            sparse_payload_id_check(n, "v", "r")
            if (ingest_combine and sparse) else None
        ),
        fold_accumulates=True,  # CC forests are pure edge-set summaries
        flatten=flatten,
        fold_backend=backend,
        merge_mode=mode,
        merge_delta=_mk_delta,
        merge_dirty_count=_mk_count,
        # Auto threshold: delta rows cost ~8 bytes each on the wire +
        # pair-rate union work; past capacity/4 gathered rows the full
        # replicated merge's sequential-scan unions win. The bench's
        # merge_delta_crossover block measures the real bound per chip;
        # delta_auto_rows carries the calibrated value in.
        merge_delta_auto_rows=(
            None if windowed is not None
            else n // 4 if delta_auto_rows is None
            else int(delta_auto_rows)
        ),
        name=f"connected-components-{merge}",
    )
    if windowed is not None:
        agg.windowed_panes = int(windowed)
    return agg


def cc_query(vertex_capacity: int, *, name: str = "cc",
             merge: str = "gather", fold_backend: str = "auto",
             compressed: bool = False, codec: str = "auto"):
    """Fuse-compatible CC query (``engine.multiquery.fuse``), tagged
    with this plan's slot capacity so ``fuse`` can refuse mismatched
    chunk schemas.

    ``compressed=False`` (default) builds the raw fold
    (``ingest_combine=False``): the fused pipeline stages each chunk
    exactly once for every query, and per-query codecs never engage.
    ``compressed=True`` keeps the ingest codec ON — when EVERY query
    of a fused set does, the fused plan's shared compress stage emits
    one multi-query compressed payload per chunk and the folds run
    through ``fold_compressed`` (the codec's ~0.25 B/edge wire win,
    recovered for fused runs). ``codec`` picks the payload format as
    in :func:`connected_components` (``"compact"`` is stack-ordered
    and un-fusable)."""
    from ..engine.multiquery import QuerySpec

    return QuerySpec(
        name=name,
        agg=connected_components(vertex_capacity, merge=merge,
                                 ingest_combine=compressed,
                                 codec=codec,
                                 fold_backend=fold_backend),
        slot_capacity=vertex_capacity,
    )


def connected_components_tree(vertex_capacity: int,
                              degree: int | None = None) -> SummaryAggregation:
    """ConnectedComponentsTree parity alias (merge-tree combine).

    ``degree`` is the SummaryTreeReduce partial-parallelism knob
    (ConnectedComponentsTree.java:28-34 passing through to
    SummaryTreeReduce.java:75): the cross-shard merge runs as a two-phase
    hierarchical tree with ``degree`` group summaries after phase 1."""
    agg = connected_components(vertex_capacity, merge="tree")
    agg.merge_degree = degree
    return agg


def cc_host_precombine(chunk):
    """Host pre-combiner: reduce a chunk to its spanning forest.

    Runs on the ingest/prefetch thread (vectorized numpy min-label
    propagation over the chunk's unique vertices) and replaces the chunk's
    edges with (vertex, chunk-local-root) pairs — connectivity-equivalent,
    but near-tree-shaped, so the device union-find fold converges in far
    fewer hook rounds. This is the reference's partial pre-aggregation
    before the global merge (SummaryBulkAggregation's per-partition fold,
    M/SummaryBulkAggregation.java:76-80) relocated to the host side of the
    ingest pipeline, overlapping device folds of earlier chunks.
    """
    m = np.asarray(chunk.valid)
    s = np.asarray(chunk.src)[m]
    d = np.asarray(chunk.dst)[m]
    if s.size == 0:
        return chunk
    ids = np.unique(np.concatenate([s, d]))
    ls = np.searchsorted(ids, s).astype(np.int64)
    ld = np.searchsorted(ids, d).astype(np.int64)
    lab = np.arange(ids.shape[0], dtype=np.int64)
    while True:
        prev = lab
        mn = np.minimum(lab[ls], lab[ld])
        lab = lab.copy()
        np.minimum.at(lab, ls, mn)
        np.minimum.at(lab, ld, mn)
        lab = np.minimum(lab, lab[lab])
        if np.array_equal(lab, prev):
            break
    # (v, root) pairs for every unique vertex: unions are connectivity-
    # equivalent to the original edges, and self-pairs keep roots "seen".
    n_out = ids.shape[0]
    cap = chunk.capacity
    src2 = np.zeros((cap,), np.int32)
    dst2 = np.zeros((cap,), np.int32)
    valid2 = np.zeros((cap,), bool)
    src2[:n_out] = ids
    dst2[:n_out] = ids[lab]
    valid2[:n_out] = True
    return chunk._replace(
        src=src2, dst=dst2,
        raw_src=np.zeros((cap,), np.int64),
        raw_dst=np.zeros((cap,), np.int64),
        valid=valid2,
    )


def labels_to_components(labels, ctx) -> list[list[int]]:
    """Decode a label array into sorted component lists of raw vertex ids —
    the structured replacement for the reference's DisjointSet.toString()
    parsing oracle (ConnectedComponentsTest.parser, :65-81)."""
    lab = np.asarray(labels)
    slots = np.nonzero(lab >= 0)[0]
    raw = ctx.decode(slots)
    comps: dict[int, list[int]] = {}
    for slot, rid in zip(slots.tolist(), raw.tolist()):
        comps.setdefault(int(lab[slot]), []).append(rid)
    return sorted(sorted(c) for c in comps.values())
