"""Streaming Connected Components — the north-star algorithm.

TPU-native re-design of ``M/library/ConnectedComponents.java:41-127`` and
``ConnectedComponentsTree.java:26-36``: the per-partition ``DisjointSet``
hash-map forest becomes a dense ``i32 parent[]`` array; ``UpdateCC.foldEdges``
(per-edge ``ds.union``) becomes a whole-chunk vectorized union
(:func:`gelly_tpu.ops.unionfind.union_edges`); ``CombineCC.reduce`` (merge
smaller forest into larger) becomes either

- a **butterfly merge-tree** over ICI (`merge="tree"`) — the
  ``SummaryTreeReduce`` log-depth reduction mapped onto the slice topology, or
- an **all_gather + stacked K×N union** (`merge="gather"`) — the flat
  ``timeWindowAll().reduce`` fan-in, vectorized.

The summary is ``(parent[i32 N], seen[bool N])``; emitted labels are the
minimum vertex slot of each component (canonical), decoded to raw ids for the
final parity oracle (component-set equality, as the reference's test asserts,
``T/example/test/ConnectedComponentsTest.java:40-47``).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.stream import EdgeStream
from ..engine.aggregation import SummaryAggregation
from ..ops import segments, unionfind


class CCSummary(NamedTuple):
    parent: jax.Array  # i32[N] union-find forest (canonical min-root)
    seen: jax.Array  # bool[N] vertices observed in the stream


def connected_components(
    vertex_capacity: int, merge: str = "tree"
) -> SummaryAggregation:
    """Build the CC aggregation over a slot space of ``vertex_capacity``.

    ``merge="tree"`` → butterfly merge-tree (ConnectedComponentsTree);
    ``merge="gather"`` → all_gather + stacked union (flat bulk aggregation).
    """
    n = vertex_capacity

    def init() -> CCSummary:
        return CCSummary(
            parent=unionfind.fresh_forest(n), seen=jnp.zeros((n,), bool)
        )

    def fold(s: CCSummary, chunk) -> CCSummary:
        parent = unionfind.union_edges(s.parent, chunk.src, chunk.dst, chunk.valid)
        seen = segments.mark_seen(s.seen, chunk.src, chunk.valid)
        seen = segments.mark_seen(seen, chunk.dst, chunk.valid)
        return CCSummary(parent, seen)

    def combine(a: CCSummary, b: CCSummary) -> CCSummary:
        return CCSummary(
            parent=unionfind.merge_forests(a.parent, b.parent),
            seen=a.seen | b.seen,
        )

    def merge_stacked(st: CCSummary) -> CCSummary:
        return CCSummary(
            parent=unionfind.merge_forest_stack(st.parent),
            seen=jnp.any(st.seen, axis=0),
        )

    def transform(s: CCSummary) -> jax.Array:
        return unionfind.component_labels(s.parent, s.seen)

    return SummaryAggregation(
        init=init,
        fold=fold,
        combine=combine,
        transform=transform,
        merge_stacked=merge_stacked if merge == "gather" else None,
        transient=False,
        name=f"connected-components-{merge}",
    )


def connected_components_tree(vertex_capacity: int) -> SummaryAggregation:
    """ConnectedComponentsTree parity alias (merge-tree combine)."""
    return connected_components(vertex_capacity, merge="tree")


def labels_to_components(labels, ctx) -> list[list[int]]:
    """Decode a label array into sorted component lists of raw vertex ids —
    the structured replacement for the reference's DisjointSet.toString()
    parsing oracle (ConnectedComponentsTest.parser, :65-81)."""
    lab = np.asarray(labels)
    slots = np.nonzero(lab >= 0)[0]
    raw = ctx.decode(slots)
    comps: dict[int, list[int]] = {}
    for slot, rid in zip(slots.tolist(), raw.tolist()):
        comps.setdefault(int(lab[slot]), []).append(rid)
    return sorted(sorted(c) for c in comps.values())
