"""Fully-dynamic degree distribution (additions + deletions).

TPU-native re-design of ``M/example/DegreeDistribution.java:42-193``, the
reference's only fully-dynamic pipeline: ±1 per endpoint per event
(``EmitVerticesWithChange``, ``:70-79``), per-vertex running degrees with
zero-degree removal (``VertexDegreeCounts``, ``:84-111``), then a
degree→vertex-count map (``DegreeDistributionMap``, ``:116-132``). Here the
keyed hash-map stages collapse into one jitted step per chunk: a ±1 scatter
into the dense degree array and a histogram rebuild over live vertices —
emission is chunk-grained with identical final state (the ITCase's
deletion-to-zero case is covered by the tests).
"""

from __future__ import annotations

from functools import partial
from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.chunk import EdgeChunk
from ..ops import segments
from ..parallel import mesh as mesh_lib, partition
from ..parallel.mesh import SHARD_AXIS


def degree_aggregate(vertex_capacity: int, count_out: bool = True,
                     count_in: bool = True, ingest_combine: bool = True,
                     codec: str = "auto", windowed: int | None = None):
    """Continuous degree aggregate as a SummaryAggregation — the engine
    form of ``getDegrees`` (SimpleEdgeStream.java:413-478, BASELINE
    workload #1): summary = dense degree vector, fold = ±1 endpoint
    scatter, combine = elementwise add.

    ``ingest_combine`` attaches the degree codec: each chunk pre-reduces on
    the host to its net degree deltas, shipping those instead of the
    chunk's edges; the device fold is a vector add / scatter-add. Same H2D
    rationale as the CC codec.

    ``codec``: ``"dense"`` (i32[n_v] delta vector per chunk — optimal at
    small n_v) / ``"sparse"`` (counted (vertex, net-delta) pairs — payload
    and host work ∝ touched vertices, the large-n_v format) / ``"auto"``
    (sparse iff ``vertex_capacity >= SPARSE_CODEC_MIN_CAPACITY``).

    ``windowed=W`` marks the plan for the engine's sliding pane ring
    (``run_aggregation(windowed=...)``): emissions are degrees over the
    last W merge windows only. Degree vectors add elementwise, so no
    summary change is needed — panes fold from fresh zeros and the ring
    sums the live suffix at O(1) amortized combines per close.
    """
    from ..engine.aggregation import (
        SummaryAggregation,
        resolve_sparse_codec,
        sparse_payload_id_check,
    )

    n = vertex_capacity
    sparse = resolve_sparse_codec(codec, n)

    def init():
        return jnp.zeros((n,), jnp.int64)

    def fold(deg, chunk):
        delta = jnp.where(chunk.event == 1, -1, 1).astype(jnp.int64)
        if count_out:
            deg = segments.masked_scatter_add(
                deg, chunk.src, delta, chunk.valid
            )
        if count_in:
            deg = segments.masked_scatter_add(
                deg, chunk.dst, delta, chunk.valid
            )
        return deg

    def host_compress(chunk):
        m = np.asarray(chunk.valid)
        ev = np.asarray(chunk.event)
        from ..utils import native

        if native.degree_deltas_available():
            # Single native pass over both endpoint columns
            # (native/chunk_combiner.cc:degree_chunk_deltas), ~4x numpy's
            # two bincounts; GIL released, so it overlaps the H2D wait.
            return native.degree_chunk_deltas(
                np.asarray(chunk.src), np.asarray(chunk.dst),
                ev if ev.any() else None, None if m.all() else m,
                n, count_out, count_in,
            )
        all_valid = bool(m.all())
        # Insertion-only chunks (the common case) pass weights=None so
        # np.bincount takes its integer path — ~4.5x faster than the
        # float-weights path the deletion case needs.
        if not ev.any():
            sign = None
        else:
            sign = np.where(ev == 1, -1, 1)
            if not all_valid:
                sign = sign[m]
        out = np.zeros((n,), np.int32)
        for on, ids in ((count_out, chunk.src), (count_in, chunk.dst)):
            if on:
                ids = np.asarray(ids)
                out += np.bincount(
                    ids if all_valid else ids[m], weights=sign, minlength=n
                ).astype(np.int32)
        return out

    def fold_compressed(deg, deltas):  # deltas: i32[K, n]
        return deg + jnp.sum(deltas, axis=0, dtype=jnp.int64)

    def host_compress_sparse(chunk) -> dict:
        m = np.asarray(chunk.valid)
        ev = np.asarray(chunk.event)
        from ..utils import native

        if native.sparse_codecs_available():
            v, d = native.degree_chunk_deltas_sparse(
                np.asarray(chunk.src), np.asarray(chunk.dst),
                ev if ev.any() else None, None if m.all() else m,
                n, count_out, count_in,
            )
        else:
            v, d = degree_pairs_numpy(
                chunk.src, chunk.dst, ev, m, n, count_out, count_in
            )
        return {"v": v, "d": d}

    def stack_sparse(payloads: list, groups: int = 1) -> dict:
        from ..engine.aggregation import (
            bucket_stack_payloads,
            group_combine_payloads,
        )

        def combine(grp: list) -> dict:
            # Net deltas sum by vertex — fewer, duplicate-free device
            # lanes per dispatch. i64 output: a group sums fold_batch
            # chunks' i32 deltas, so the per-chunk bound no longer holds.
            v, d = _sum_deltas(
                np.concatenate([q["v"] for q in grp]),
                np.concatenate([q["d"] for q in grp]).astype(np.int64),
            )
            return {"v": v, "d": d}

        payloads = group_combine_payloads(
            payloads, groups, combine,
            {"v": np.empty(0, np.int32), "d": np.empty(0, np.int64)},
        )
        return bucket_stack_payloads(payloads, {"v": -1, "d": 0})

    def fold_compressed_sparse(deg, payload):
        # payload: {"v": i32[K, cap], "d": int[K, cap]} counted (vertex,
        # net-delta) pairs, -1-padded. "d" is i32 straight from the
        # per-chunk codec but i64 after the group pre-combine (cross-chunk
        # sums exceed the per-chunk bound) — do NOT narrow it here.
        v = payload["v"].reshape(-1)
        ok = v >= 0
        return segments.masked_scatter_add(
            deg, jnp.where(ok, v, 0), payload["d"].reshape(-1), ok
        )

    if windowed is not None and int(windowed) < 1:
        raise ValueError(f"windowed must be >= 1 pane, got {windowed}")
    agg = SummaryAggregation(
        init=init,
        fold=fold,
        combine=lambda a, b: a + b,
        transform=None,
        host_compress=(
            (host_compress_sparse if sparse else host_compress)
            if ingest_combine else None
        ),
        fold_compressed=(
            (fold_compressed_sparse if sparse else fold_compressed)
            if ingest_combine else None
        ),
        stack_payloads=(
            stack_sparse if (ingest_combine and sparse) else None
        ),
        # Sparse-pair wire pad values (tenant compressed tiers stack
        # per-chunk payloads themselves; -1 lanes fold as no-ops) +
        # the producer-payload id range check (wire-ingest parity).
        codec_pad_values=(
            {"v": -1, "d": 0} if (ingest_combine and sparse) else None
        ),
        codec_payload_check=(
            sparse_payload_id_check(n, "v")
            if (ingest_combine and sparse) else None
        ),
        fold_accumulates=True,  # degree vectors add elementwise
        name="degree-aggregate",
    )
    if windowed is not None:
        agg.windowed_panes = int(windowed)
    return agg


def degrees_query(vertex_capacity: int, *, name: str = "degrees",
                  count_out: bool = True, count_in: bool = True,
                  compressed: bool = False, codec: str = "auto"):
    """Fuse-compatible degree query (``engine.multiquery.fuse``): the
    ±1-scatter fold (``ingest_combine=False`` by default — see
    :func:`~gelly_tpu.library.connected_components.cc_query` for the
    shared-chunk rationale; ``compressed=True`` keeps the delta codec
    on for fused codec sharing). ``count_out``/``count_in`` pick the
    direction, so e.g. out- and in-degree can ride one fused dispatch
    as two named queries."""
    from ..engine.multiquery import QuerySpec

    return QuerySpec(
        name=name,
        agg=degree_aggregate(vertex_capacity, count_out=count_out,
                             count_in=count_in,
                             ingest_combine=compressed, codec=codec),
        slot_capacity=vertex_capacity,
    )


def _sum_deltas(ids: np.ndarray, deltas: np.ndarray):
    """Sum deltas by vertex id, dropping zero nets. Accumulates in the
    deltas dtype — callers summing across chunks pass i64."""
    uniq, inv = np.unique(ids, return_inverse=True)
    acc = np.zeros(uniq.shape[0], deltas.dtype)
    np.add.at(acc, inv, deltas)
    nz = acc != 0
    return uniq[nz].astype(np.int32), acc[nz]


def degree_pairs_numpy(src, dst, event, valid, n_v: int,
                       count_out: bool = True, count_in: bool = True):
    """Pure-numpy fallback for the native sparse degree codec: counted
    (vertex, net-delta) pairs (zero net deltas omitted)."""
    m = None if valid is None else np.asarray(valid, bool)
    ev = None if event is None else np.asarray(event)
    ids_parts, delta_parts = [], []
    for on, col in ((count_out, src), (count_in, dst)):
        if not on:
            continue
        col = np.asarray(col)
        d = (
            np.ones(col.shape[0], np.int64) if ev is None or not ev.any()
            else np.where(ev == 1, -1, 1).astype(np.int64)
        )
        if m is not None and not m.all():
            col, d = col[m], d[m]
        ids_parts.append(col)
        delta_parts.append(d)
    if not ids_parts:
        return np.empty(0, np.int32), np.empty(0, np.int32)
    ids = np.concatenate(ids_parts)
    deltas = np.concatenate(delta_parts)
    if ids.size == 0:
        return np.empty(0, np.int32), np.empty(0, np.int32)
    if ids.min() < 0 or ids.max() >= n_v:
        raise ValueError("degree_pairs_numpy: vertex slot out of range")
    v, d = _sum_deltas(ids, deltas)
    return v, d.astype(np.int32)  # per-chunk nets fit i32 (native parity)


def degree_distribution(stream, max_degree: int | None = None
                        ) -> "DegreeDistributionStream":
    return DegreeDistributionStream(stream, max_degree)


class DegreeDistributionStream:
    def __init__(self, stream, max_degree: int | None = None):
        self.stream = stream
        # Degrees are bounded by 2x the edge events touching a vertex; the
        # histogram needs a static size. Default: vertex capacity.
        self.max_degree = (
            int(max_degree) if max_degree is not None
            else stream.ctx.vertex_capacity
        )

    def __iter__(self) -> Iterator[jax.Array]:
        """Yields the degree histogram (i64[max_degree+1], index = degree,
        entry = #vertices with that degree; degree-0/negative vertices are
        excluded per VertexDegreeCounts' removal) after each chunk."""
        n = self.stream.ctx.vertex_capacity
        d_max = self.max_degree

        @jax.jit
        def step(deg, c):
            delta = jnp.where(c.event == 1, -1, 1).astype(jnp.int64)
            deg = segments.masked_scatter_add(deg, c.src, delta, c.valid)
            deg = segments.masked_scatter_add(deg, c.dst, delta, c.valid)
            live = deg > 0
            hist = jnp.zeros((d_max + 1,), jnp.int64)
            idx = jnp.clip(deg, 0, d_max)
            hist = hist.at[jnp.where(live, idx, 0)].add(
                live.astype(jnp.int64), mode="drop"
            )
            return deg, hist, jnp.max(deg)

        deg = jnp.zeros((n,), jnp.int64)
        for c in self.stream:
            deg, hist, peak = step(deg, c)
            if int(peak) > d_max:
                raise ValueError(
                    f"degree {int(peak)} exceeds max_degree {d_max}; "
                    f"raise max_degree"
                )
            yield hist

    def final_distribution(self) -> dict[int, int]:
        hist = None
        for hist in self:
            pass
        if hist is None:
            return {}
        h = np.asarray(hist)
        return {int(d): int(h[d]) for d in np.nonzero(h)[0]}


class ShardedDegrees:
    """Vertex-hash-partitioned degree state over the mesh — the ``keyBy``
    parallelism strategy (SURVEY.md §2.8 row 2: the reference co-locates a
    vertex's edges on one subtask via hash shuffle,
    ``M/SimpleEdgeStream.java:492``).

    Three modes:

    - ``mode="auto"`` (default): the keyed exchange below, but a chunk
      whose exchange buckets overflow is left unapplied and replayed
      through the broadcast step — skewed streams stay correct at
      broadcast cost for the hot chunks only
      (``self.stats["fallback_chunks"]`` counts them).
    - ``mode="exchange"``: the chunk is split evenly across devices; each
      device emits (endpoint, ±1) pairs for its slice and a single
      ``all_to_all`` (:func:`parallel.partition.repartition_by_key`)
      delivers every pair to the device owning that vertex — per-device
      work is O(E/S), the true keyBy shuffle. Bucket overflow is counted
      in ``self.stats["dropped"]`` and raises (strict mode; raise
      ``bucket_slack`` for skewed streams).
    - ``mode="broadcast"``: every device scans the whole replicated chunk
      and masks to its owned endpoints — zero exchange buffers, but
      per-device work stays O(E). The skew-proof fallback.
    """

    def __init__(self, stream, mesh=None, count_out=True, count_in=True,
                 mode: str = "auto", bucket_slack: float = 2.0):
        if mode not in ("auto", "exchange", "broadcast"):
            raise ValueError(f"mode must be auto/exchange/broadcast, got {mode}")
        self.stream = stream
        self.mesh = mesh if mesh is not None else mesh_lib.make_mesh()
        self.count_out = count_out
        self.count_in = count_in
        self.mode = mode
        self.bucket_slack = bucket_slack
        self.stats = {"dropped": 0}
        n = stream.ctx.vertex_capacity
        self.per_shard = partition.slots_per_shard(
            n, mesh_lib.num_shards(self.mesh)
        )

    def _step_fn(self, mode: str):
        count_out, count_in = self.count_out, self.count_in
        m = self.mesh
        S = mesh_lib.num_shards(m)
        sharded = NamedSharding(m, P(SHARD_AXIS))

        if mode == "broadcast":
            def body(deg_local, chunk):
                # deg_local: this device's [per] slice; chunk replicated.
                delta = jnp.where(chunk.event == 1, -1, 1).astype(jnp.int64)
                if count_out:
                    mine = partition.owned_mask(chunk.src, S)
                    deg_local = segments.masked_scatter_add(
                        deg_local, partition.to_local_slot(chunk.src, S),
                        delta, chunk.valid & mine,
                    )
                if count_in:
                    mine = partition.owned_mask(chunk.dst, S)
                    deg_local = segments.masked_scatter_add(
                        deg_local, partition.to_local_slot(chunk.dst, S),
                        delta, chunk.valid & mine,
                    )
                return deg_local, jnp.zeros((1,), jnp.int64)

            in_chunk_spec = P()
        else:
            def body(deg_local, chunk_slice):
                # chunk_slice: this device's [1, L] slice of the split chunk.
                c = EdgeChunk(*(x[0] for x in chunk_slice))
                delta = jnp.where(c.event == 1, -1, 1).astype(jnp.int64)
                keys, deltas, valids = [], [], []
                if count_out:
                    keys.append(c.src)
                    deltas.append(delta)
                    valids.append(c.valid)
                if count_in:
                    keys.append(c.dst)
                    deltas.append(delta)
                    valids.append(c.valid)
                key = jnp.concatenate(keys)
                dd = jnp.concatenate(deltas)
                vv = jnp.concatenate(valids)
                cap = partition.default_bucket_capacity(
                    key.shape[0], S, self.bucket_slack
                )
                key_r, dd_r, valid_r, dropped = partition.repartition_by_key(
                    key, dd, vv, S, cap
                )
                applied = segments.masked_scatter_add(
                    deg_local, partition.to_local_slot(key_r, S),
                    dd_r, valid_r,
                )
                # An overflowing chunk is left UNAPPLIED (dropped is the
                # same psum on every device, so all shards agree): auto
                # mode replays it through the broadcast step; strict mode
                # raises with the state still consistent.
                deg_local = jnp.where(dropped == 0, applied, deg_local)
                return deg_local, dropped.astype(jnp.int64)[None]

            in_chunk_spec = P(SHARD_AXIS)

        @partial(jax.jit, out_shardings=(sharded, None))
        def step(deg, chunk):
            if mode != "broadcast":
                chunk = partition.split_chunk(chunk, S)
            deg2, dropped = mesh_lib.shard_map_fn(
                m, body, in_specs=(P(SHARD_AXIS), in_chunk_spec),
                out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
            )(deg, chunk)
            # dropped is identical on every shard (psum); take shard 0.
            return deg2, dropped[0]

        return step

    def final_degrees(self) -> dict[int, int]:
        n = self.stream.ctx.vertex_capacity
        mode = self.mode
        step = self._step_fn("broadcast" if mode == "broadcast" else "exchange")
        fallback = self._step_fn("broadcast") if mode == "auto" else None
        deg = jax.device_put(
            jnp.zeros((n,), jnp.int64), NamedSharding(self.mesh, P(SHARD_AXIS))
        )
        seen = np.zeros((n,), bool)
        pending: list = []  # (chunk, dropped_scalar) awaiting the drop check
        self.stats["fallback_chunks"] = 0

        def check_drops():
            nonlocal deg
            dropped_total = 0
            for c, d in pending:
                nd = int(d)
                if not nd:
                    continue
                if fallback is not None:
                    # The overflowing chunk was left unapplied: replay it
                    # through the skew-proof broadcast step.
                    deg, _ = fallback(deg, c)
                    self.stats["fallback_chunks"] += 1
                else:
                    dropped_total += nd
            pending.clear()
            if dropped_total:
                self.stats["dropped"] += dropped_total
                raise ValueError(
                    f"{dropped_total} endpoint updates overflowed the "
                    f"exchange buckets; raise bucket_slack or use "
                    f"mode='auto' (no silent drops)"
                )

        for i, c in enumerate(self.stream):
            ok = np.asarray(c.valid)
            # Directional parity with DegreeStream: an endpoint is
            # "touched" only for the directions being counted
            # (DegreeTypeSeparator, M/SimpleEdgeStream.java:440-459).
            if self.count_out:
                seen[np.asarray(c.src)[ok]] = True
            if self.count_in:
                seen[np.asarray(c.dst)[ok]] = True
            deg, dropped = step(deg, c)
            if mode != "broadcast":
                pending.append((c, dropped))
                # One host sync every 8 chunks: fail fast (strict) or
                # replay overflowed chunks (auto) without serializing the
                # dispatch pipeline.
                if i % 8 == 7:
                    check_drops()
        check_drops()
        # De-stripe the shard-concatenated state back to global slot order.
        out = partition.unstripe(np.asarray(deg), mesh_lib.num_shards(self.mesh))
        ctx = self.stream.ctx
        slots = np.nonzero(seen)[0]
        raw = ctx.decode(slots)
        return {int(r): int(out[s]) for s, r in zip(slots, raw)}


def sharded_degrees(stream, mesh=None, count_out=True, count_in=True,
                    mode: str = "auto", bucket_slack: float = 2.0
                    ) -> ShardedDegrees:
    return ShardedDegrees(stream, mesh, count_out, count_in, mode,
                          bucket_slack)
