"""Fully-dynamic degree distribution (additions + deletions).

TPU-native re-design of ``M/example/DegreeDistribution.java:42-193``, the
reference's only fully-dynamic pipeline: ±1 per endpoint per event
(``EmitVerticesWithChange``, ``:70-79``), per-vertex running degrees with
zero-degree removal (``VertexDegreeCounts``, ``:84-111``), then a
degree→vertex-count map (``DegreeDistributionMap``, ``:116-132``). Here the
keyed hash-map stages collapse into one jitted step per chunk: a ±1 scatter
into the dense degree array and a histogram rebuild over live vertices —
emission is chunk-grained with identical final state (the ITCase's
deletion-to-zero case is covered by the tests).
"""

from __future__ import annotations

from functools import partial
from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops import segments
from ..parallel import mesh as mesh_lib, partition
from ..parallel.mesh import SHARD_AXIS


def degree_distribution(stream, max_degree: int | None = None
                        ) -> "DegreeDistributionStream":
    return DegreeDistributionStream(stream, max_degree)


class DegreeDistributionStream:
    def __init__(self, stream, max_degree: int | None = None):
        self.stream = stream
        # Degrees are bounded by 2x the edge events touching a vertex; the
        # histogram needs a static size. Default: vertex capacity.
        self.max_degree = (
            int(max_degree) if max_degree is not None
            else stream.ctx.vertex_capacity
        )

    def __iter__(self) -> Iterator[jax.Array]:
        """Yields the degree histogram (i64[max_degree+1], index = degree,
        entry = #vertices with that degree; degree-0/negative vertices are
        excluded per VertexDegreeCounts' removal) after each chunk."""
        n = self.stream.ctx.vertex_capacity
        d_max = self.max_degree

        @jax.jit
        def step(deg, c):
            delta = jnp.where(c.event == 1, -1, 1).astype(jnp.int64)
            deg = segments.masked_scatter_add(deg, c.src, delta, c.valid)
            deg = segments.masked_scatter_add(deg, c.dst, delta, c.valid)
            live = deg > 0
            hist = jnp.zeros((d_max + 1,), jnp.int64)
            idx = jnp.clip(deg, 0, d_max)
            hist = hist.at[jnp.where(live, idx, 0)].add(
                live.astype(jnp.int64), mode="drop"
            )
            return deg, hist, jnp.max(deg)

        deg = jnp.zeros((n,), jnp.int64)
        for c in self.stream:
            deg, hist, peak = step(deg, c)
            if int(peak) > d_max:
                raise ValueError(
                    f"degree {int(peak)} exceeds max_degree {d_max}; "
                    f"raise max_degree"
                )
            yield hist

    def final_distribution(self) -> dict[int, int]:
        hist = None
        for hist in self:
            pass
        if hist is None:
            return {}
        h = np.asarray(hist)
        return {int(d): int(h[d]) for d in np.nonzero(h)[0]}


class ShardedDegrees:
    """Vertex-hash-partitioned degree state over the mesh — the ``keyBy``
    parallelism strategy (SURVEY.md §2.8 row 2: the reference co-locates a
    vertex's edges on one subtask via hash shuffle,
    ``M/SimpleEdgeStream.java:492``). Here the degree array is
    range-partitioned over the shard axis; each device sees the whole
    (small) chunk broadcast over ICI and scatter-adds only the endpoints it
    owns — broadcast-then-mask instead of a ragged all_to_all, so the
    per-device state is a dense slice and no reshuffle buffer is needed.
    """

    def __init__(self, stream, mesh=None, count_out=True, count_in=True):
        self.stream = stream
        self.mesh = mesh if mesh is not None else mesh_lib.make_mesh()
        self.count_out = count_out
        self.count_in = count_in
        n = stream.ctx.vertex_capacity
        self.per_shard = partition.slots_per_shard(
            n, mesh_lib.num_shards(self.mesh)
        )

    def _step_fn(self):
        per = self.per_shard
        count_out, count_in = self.count_out, self.count_in
        m = self.mesh
        sharded = NamedSharding(m, P(SHARD_AXIS))

        def body(deg_local, chunk):
            # deg_local: this device's [per] slice; chunk replicated.
            delta = jnp.where(chunk.event == 1, -1, 1).astype(jnp.int64)
            if count_out:
                mine = partition.owned_mask(chunk.src, per)
                deg_local = segments.masked_scatter_add(
                    deg_local, partition.to_local_slot(chunk.src, per),
                    delta, chunk.valid & mine,
                )
            if count_in:
                mine = partition.owned_mask(chunk.dst, per)
                deg_local = segments.masked_scatter_add(
                    deg_local, partition.to_local_slot(chunk.dst, per),
                    delta, chunk.valid & mine,
                )
            return deg_local

        @partial(jax.jit, out_shardings=sharded)
        def step(deg, chunk):
            return mesh_lib.shard_map_fn(
                m, body, in_specs=(P(SHARD_AXIS), P()), out_specs=P(SHARD_AXIS),
            )(deg, chunk)

        return step

    def final_degrees(self) -> dict[int, int]:
        n = self.stream.ctx.vertex_capacity
        step = self._step_fn()
        deg = jax.device_put(
            jnp.zeros((n,), jnp.int64), NamedSharding(self.mesh, P(SHARD_AXIS))
        )
        seen = np.zeros((n,), bool)
        for c in self.stream:
            ok = np.asarray(c.valid)
            # Directional parity with DegreeStream: an endpoint is
            # "touched" only for the directions being counted
            # (DegreeTypeSeparator, M/SimpleEdgeStream.java:440-459).
            if self.count_out:
                seen[np.asarray(c.src)[ok]] = True
            if self.count_in:
                seen[np.asarray(c.dst)[ok]] = True
            deg = step(deg, c)
        out = np.asarray(deg)
        ctx = self.stream.ctx
        slots = np.nonzero(seen)[0]
        raw = ctx.decode(slots)
        return {int(r): int(out[s]) for s, r in zip(slots, raw)}


def sharded_degrees(stream, mesh=None, count_out=True, count_in=True
                    ) -> ShardedDegrees:
    return ShardedDegrees(stream, mesh, count_out, count_in)
