"""Fully-dynamic degree distribution (additions + deletions).

TPU-native re-design of ``M/example/DegreeDistribution.java:42-193``, the
reference's only fully-dynamic pipeline: ±1 per endpoint per event
(``EmitVerticesWithChange``, ``:70-79``), per-vertex running degrees with
zero-degree removal (``VertexDegreeCounts``, ``:84-111``), then a
degree→vertex-count map (``DegreeDistributionMap``, ``:116-132``). Here the
keyed hash-map stages collapse into one jitted step per chunk: a ±1 scatter
into the dense degree array and a histogram rebuild over live vertices —
emission is chunk-grained with identical final state (the ITCase's
deletion-to-zero case is covered by the tests).
"""

from __future__ import annotations

from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import segments


def degree_distribution(stream, max_degree: int | None = None
                        ) -> "DegreeDistributionStream":
    return DegreeDistributionStream(stream, max_degree)


class DegreeDistributionStream:
    def __init__(self, stream, max_degree: int | None = None):
        self.stream = stream
        # Degrees are bounded by 2x the edge events touching a vertex; the
        # histogram needs a static size. Default: vertex capacity.
        self.max_degree = (
            int(max_degree) if max_degree is not None
            else stream.ctx.vertex_capacity
        )

    def __iter__(self) -> Iterator[jax.Array]:
        """Yields the degree histogram (i64[max_degree+1], index = degree,
        entry = #vertices with that degree; degree-0/negative vertices are
        excluded per VertexDegreeCounts' removal) after each chunk."""
        n = self.stream.ctx.vertex_capacity
        d_max = self.max_degree

        @jax.jit
        def step(deg, c):
            delta = jnp.where(c.event == 1, -1, 1).astype(jnp.int64)
            deg = segments.masked_scatter_add(deg, c.src, delta, c.valid)
            deg = segments.masked_scatter_add(deg, c.dst, delta, c.valid)
            live = deg > 0
            hist = jnp.zeros((d_max + 1,), jnp.int64)
            idx = jnp.clip(deg, 0, d_max)
            hist = hist.at[jnp.where(live, idx, 0)].add(
                live.astype(jnp.int64), mode="drop"
            )
            return deg, hist, jnp.max(deg)

        deg = jnp.zeros((n,), jnp.int64)
        for c in self.stream:
            deg, hist, peak = step(deg, c)
            if int(peak) > d_max:
                raise ValueError(
                    f"degree {int(peak)} exceeds max_degree {d_max}; "
                    f"raise max_degree"
                )
            yield hist

    def final_distribution(self) -> dict[int, int]:
        hist = None
        for hist in self:
            pass
        if hist is None:
            return {}
        h = np.asarray(hist)
        return {int(d): int(h[d]) for d in np.nonzero(h)[0]}
