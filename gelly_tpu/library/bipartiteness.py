"""Streaming Bipartiteness Check.

TPU-native re-design of ``M/library/BipartitenessCheck.java:39-133``: the
``Candidates`` component-map/sign machinery becomes a parity union-find
(:mod:`gelly_tpu.ops.parity_unionfind`) proven equivalent on the reference's
test vectors (``T/example/test/BipartitenessCheckTest.java:40-44,63-65``).
Each edge asserts its endpoints take opposite colors; an odd cycle flips the
sticky ``failed`` bit, the analog of the merge collapsing to ``(false, {})``.

The emission is a :class:`BipartitenessResult`; ``to_candidates`` renders the
reference's observable shape (success flag + per-component signed vertex
sets).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.aggregation import SummaryAggregation
from ..ops import parity_unionfind as puf, segments


class BipartiteSummary(NamedTuple):
    forest: puf.ParityForest
    seen: jax.Array  # bool[N]


def parity_labels_numpy(src: np.ndarray, dst: np.ndarray,
                        valid: np.ndarray | None, n_v: int):
    """Pure-numpy fallback for the native parity combiner.

    Returns ``(labels i32[n_v], parity u8[n_v], conflict bool)``: the
    chunk's spanning forest plus each touched vertex's 2-coloring parity
    relative to its root, and whether the chunk alone contains an odd
    cycle. Parity follows original graph edges (propagated from the roots),
    not the compressed star — path parity is a graph property.
    """
    from .connected_components import cc_labels_numpy

    if valid is not None:
        m = np.asarray(valid, bool)
        src, dst = np.asarray(src)[m], np.asarray(dst)[m]
    src = np.asarray(src, np.int64)
    dst = np.asarray(dst, np.int64)
    labels = cc_labels_numpy(src, dst, None, n_v)
    parity = np.zeros((n_v,), np.uint8)
    if src.size == 0:
        return labels, parity, False
    known = labels == np.arange(n_v)  # roots seed color 0
    # BFS-style relaxation over the chunk's edges; each round extends the
    # colored frontier by one hop. Any valid per-chunk 2-coloring works
    # (global consistency is the device merge's job), and for a bipartite
    # chunk the propagated coloring is the unique one per component.
    for _ in range(n_v):
        fwd = known[src] & ~known[dst]
        bwd = known[dst] & ~known[src]
        if not (fwd.any() or bwd.any()):
            break
        parity[dst[fwd]] = parity[src[fwd]] ^ 1
        known[dst[fwd]] = True
        parity[src[bwd]] = parity[dst[bwd]] ^ 1
        known[src[bwd]] = True
    conflict = bool((parity[src] == parity[dst]).any())
    return labels, parity, conflict


def parity_pairs_numpy(src: np.ndarray, dst: np.ndarray,
                       valid: np.ndarray | None, n_v: int):
    """Pure-numpy fallback for the native sparse parity combiner: counted
    (vertex, root, parity) triples + chunk odd-cycle flag — work and
    payload proportional to touched vertices, never ``n_v``."""
    if valid is not None:
        m = np.asarray(valid, bool)
        src, dst = np.asarray(src)[m], np.asarray(dst)[m]
    src = np.asarray(src, np.int64)
    dst = np.asarray(dst, np.int64)
    empty = (np.empty(0, np.int32), np.empty(0, np.int32),
             np.empty(0, np.uint8), False)
    if src.size == 0:
        return empty
    ids = np.unique(np.concatenate([src, dst]))
    if ids[0] < 0 or ids[-1] >= n_v:
        raise ValueError("parity_pairs_numpy: vertex slot out of range")
    ls = np.searchsorted(ids, src)
    ld = np.searchsorted(ids, dst)
    labels, parity, conflict = parity_labels_numpy(
        ls, ld, None, ids.shape[0]
    )
    return (ids.astype(np.int32), ids[labels].astype(np.int32),
            parity.astype(np.uint8), conflict)


class BipartitenessResult(NamedTuple):
    ok: jax.Array  # bool[] — graph (still) 2-colorable
    labels: jax.Array  # i32[N] component label (min slot), -1 unseen
    colors: jax.Array  # i32[N] 0/1 parity color, -1 unseen


def bipartiteness_check(vertex_capacity: int,
                        ingest_combine: bool = True,
                        codec: str = "auto") -> SummaryAggregation:
    """``ingest_combine`` (default on) attaches the ingest codec: chunks are
    pre-reduced on the host to (spanning forest, parity, conflict) — the
    native parity union-find combiner (native/chunk_combiner.cc) — and the
    device unions the parity-carrying star constraints. Same H2D compression
    rationale as the CC codec.

    ``codec``: ``"dense"`` (i32[n_v] labels + u8[n_v] parity per chunk) /
    ``"sparse"`` (counted (vertex, root, parity) triples — payload and
    host work ∝ touched vertices) / ``"auto"`` (sparse iff
    ``vertex_capacity >= SPARSE_CODEC_MIN_CAPACITY``); see
    :func:`~gelly_tpu.library.connected_components.connected_components`.
    """
    from ..engine.aggregation import (
        resolve_sparse_codec,
        sparse_payload_id_check,
    )

    n = vertex_capacity
    sparse = resolve_sparse_codec(codec, n)

    def init() -> BipartiteSummary:
        return BipartiteSummary(
            forest=puf.fresh_parity_forest(n), seen=jnp.zeros((n,), bool)
        )

    def fold(s: BipartiteSummary, chunk) -> BipartiteSummary:
        # Each edge constrains endpoints to opposite colors (q=1), the
        # +/- signs of edgeToCandidate (M/library/BipartitenessCheck.java:54-61).
        q = jnp.ones_like(chunk.src, dtype=jnp.int32)
        forest = puf.union_edges_parity(
            s.forest, chunk.src, chunk.dst, q, chunk.valid
        )
        seen = segments.mark_seen(s.seen, chunk.src, chunk.valid)
        seen = segments.mark_seen(seen, chunk.dst, chunk.valid)
        return BipartiteSummary(forest, seen)

    def combine(a: BipartiteSummary, b: BipartiteSummary) -> BipartiteSummary:
        return BipartiteSummary(
            forest=puf.merge_parity_forests(a.forest, b.forest),
            seen=a.seen | b.seen,
        )

    def merge_stacked(st: BipartiteSummary) -> BipartiteSummary:
        return BipartiteSummary(
            forest=puf.merge_parity_stack(st.forest),
            seen=jnp.any(st.seen, axis=0),
        )

    def transform(s: BipartiteSummary) -> BipartitenessResult:
        labels, colors = puf.two_coloring(s.forest, s.seen)
        return BipartitenessResult(~s.forest.failed, labels, colors)

    def host_compress(chunk):
        from .connected_components import _native_ok

        if _native_ok():
            from ..utils.native import parity_chunk_combine

            labels, parity, conflict = parity_chunk_combine(
                np.asarray(chunk.src), np.asarray(chunk.dst),
                np.asarray(chunk.valid), n,
            )
        else:
            labels, parity, conflict = parity_labels_numpy(
                chunk.src, chunk.dst, chunk.valid, n
            )
        return {
            "labels": labels,
            "parity": parity.astype(np.int8),
            "conflict": np.bool_(conflict),
        }

    def fold_compressed(s: BipartiteSummary, payload) -> BipartiteSummary:
        # payload leaves are [K, n]-stacked chunk forests (+[K] conflicts).
        labels = payload["labels"]
        k = labels.shape[0]
        present = jnp.any(labels >= 0, axis=0)
        v = jnp.broadcast_to(
            jnp.arange(n, dtype=jnp.int32), (k, n)
        ).reshape(-1)
        lab = labels.reshape(-1)
        ok = lab >= 0
        q = payload["parity"].reshape(-1).astype(jnp.int32)
        forest = puf.union_edges_parity(
            s.forest._replace(
                failed=s.forest.failed | jnp.any(payload["conflict"])
            ),
            v, jnp.where(ok, lab, 0).astype(jnp.int32), q, ok,
        )
        return BipartiteSummary(forest, s.seen | present)

    def host_compress_sparse(chunk) -> dict:
        from ..utils import native

        if native.sparse_codecs_available():
            v, r, p, conflict = native.parity_chunk_combine_sparse(
                np.asarray(chunk.src), np.asarray(chunk.dst),
                np.asarray(chunk.valid), n,
            )
        else:
            v, r, p, conflict = parity_pairs_numpy(
                chunk.src, chunk.dst, chunk.valid, n
            )
        return {"v": v, "r": r, "p": p.astype(np.int8),
                "conflict": np.bool_(conflict)}

    def stack_sparse(payloads: list, groups: int = 1) -> dict:
        # No host-side group combine here (unlike CC): the stacked rows
        # stay one-per-chunk; ``groups`` only names the mesh split.
        from ..engine.aggregation import bucket_stack_payloads

        return bucket_stack_payloads(payloads, {"v": -1, "r": 0, "p": 0})

    def fold_compressed_sparse(s: BipartiteSummary,
                               payload) -> BipartiteSummary:
        # payload: K chunks' counted (vertex, root, parity) triples,
        # -1-padded, + [K] chunk-local conflict flags.
        v = payload["v"].reshape(-1)
        ok = v >= 0
        vi = jnp.where(ok, v, 0)
        q = payload["p"].reshape(-1).astype(jnp.int32)
        base = s.forest._replace(
            failed=s.forest.failed | jnp.any(payload["conflict"])
        )
        if 4 * v.size <= n:
            # Compacted-root-space parity union: per-round work ∝ pairs
            # (same trace-time shape heuristic as the CC sparse fold).
            forest = puf.union_pairs_parity_compact(
                base, vi, payload["r"].reshape(-1), q, ok
            )
        else:
            forest = puf.union_edges_parity(
                base, vi, payload["r"].reshape(-1), q, ok
            )
        seen = segments.mark_seen(s.seen, vi, ok)
        return BipartiteSummary(forest, seen)

    return SummaryAggregation(
        init=init,
        fold=fold,
        combine=combine,
        transform=transform,
        merge_stacked=merge_stacked,
        host_compress=(
            (host_compress_sparse if sparse else host_compress)
            if ingest_combine else None
        ),
        fold_compressed=(
            (fold_compressed_sparse if sparse else fold_compressed)
            if ingest_combine else None
        ),
        stack_payloads=(
            stack_sparse if (ingest_combine and sparse) else None
        ),
        # Sparse-triple wire pad values (tenant compressed tiers stack
        # per-chunk payloads themselves; -1 lanes fold as no-ops) +
        # the producer-payload id range check (wire-ingest parity).
        codec_pad_values=(
            {"v": -1, "r": 0, "p": 0}
            if (ingest_combine and sparse) else None
        ),
        codec_payload_check=(
            sparse_payload_id_check(n, "v", "r")
            if (ingest_combine and sparse) else None
        ),
        fold_accumulates=True,  # parity forests are pure edge-set summaries
        name="bipartiteness-check",
    )


def bipartiteness_query(vertex_capacity: int, *,
                        name: str = "bipartiteness",
                        compressed: bool = False, codec: str = "auto"):
    """Fuse-compatible bipartiteness query (``engine.multiquery.fuse``):
    the parity-union fold (``ingest_combine=False`` by default — see
    :func:`~gelly_tpu.library.connected_components.cc_query` for the
    shared-chunk rationale; ``compressed=True`` keeps the parity codec
    on for fused codec sharing)."""
    from ..engine.multiquery import QuerySpec

    return QuerySpec(
        name=name,
        agg=bipartiteness_check(vertex_capacity,
                                ingest_combine=compressed, codec=codec),
        slot_capacity=vertex_capacity,
    )


def to_candidates(result: BipartitenessResult, ctx):
    """Render the reference's observable: (success, {component: {vertex:
    sign}}) with sign True for the root's color side — the Candidates
    toString oracle (BipartitenessCheckTest.java:40-44). Returns
    ``(False, {})`` on failure, matching fail()'s collapse."""
    if not bool(result.ok):
        return False, {}
    lab = np.asarray(result.labels)
    col = np.asarray(result.colors)
    comps: dict[int, dict[int, bool]] = {}
    slots = np.nonzero(lab >= 0)[0]
    raw = ctx.decode(slots)
    for slot, rid in zip(slots.tolist(), raw.tolist()):
        root_raw = int(ctx.decode(np.array([lab[slot]]))[0])
        comps.setdefault(root_raw, {})[rid] = bool(col[slot] == 0)
    return True, comps
