"""Streaming Bipartiteness Check.

TPU-native re-design of ``M/library/BipartitenessCheck.java:39-133``: the
``Candidates`` component-map/sign machinery becomes a parity union-find
(:mod:`gelly_tpu.ops.parity_unionfind`) proven equivalent on the reference's
test vectors (``T/example/test/BipartitenessCheckTest.java:40-44,63-65``).
Each edge asserts its endpoints take opposite colors; an odd cycle flips the
sticky ``failed`` bit, the analog of the merge collapsing to ``(false, {})``.

The emission is a :class:`BipartitenessResult`; ``to_candidates`` renders the
reference's observable shape (success flag + per-component signed vertex
sets).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.aggregation import SummaryAggregation
from ..ops import parity_unionfind as puf, segments


class BipartiteSummary(NamedTuple):
    forest: puf.ParityForest
    seen: jax.Array  # bool[N]


class BipartitenessResult(NamedTuple):
    ok: jax.Array  # bool[] — graph (still) 2-colorable
    labels: jax.Array  # i32[N] component label (min slot), -1 unseen
    colors: jax.Array  # i32[N] 0/1 parity color, -1 unseen


def bipartiteness_check(vertex_capacity: int) -> SummaryAggregation:
    n = vertex_capacity

    def init() -> BipartiteSummary:
        return BipartiteSummary(
            forest=puf.fresh_parity_forest(n), seen=jnp.zeros((n,), bool)
        )

    def fold(s: BipartiteSummary, chunk) -> BipartiteSummary:
        # Each edge constrains endpoints to opposite colors (q=1), the
        # +/- signs of edgeToCandidate (M/library/BipartitenessCheck.java:54-61).
        q = jnp.ones_like(chunk.src, dtype=jnp.int32)
        forest = puf.union_edges_parity(
            s.forest, chunk.src, chunk.dst, q, chunk.valid
        )
        seen = segments.mark_seen(s.seen, chunk.src, chunk.valid)
        seen = segments.mark_seen(seen, chunk.dst, chunk.valid)
        return BipartiteSummary(forest, seen)

    def combine(a: BipartiteSummary, b: BipartiteSummary) -> BipartiteSummary:
        return BipartiteSummary(
            forest=puf.merge_parity_forests(a.forest, b.forest),
            seen=a.seen | b.seen,
        )

    def merge_stacked(st: BipartiteSummary) -> BipartiteSummary:
        return BipartiteSummary(
            forest=puf.merge_parity_stack(st.forest),
            seen=jnp.any(st.seen, axis=0),
        )

    def transform(s: BipartiteSummary) -> BipartitenessResult:
        labels, colors = puf.two_coloring(s.forest, s.seen)
        return BipartitenessResult(~s.forest.failed, labels, colors)

    return SummaryAggregation(
        init=init,
        fold=fold,
        combine=combine,
        transform=transform,
        merge_stacked=merge_stacked,
        name="bipartiteness-check",
    )


def to_candidates(result: BipartitenessResult, ctx):
    """Render the reference's observable: (success, {component: {vertex:
    sign}}) with sign True for the root's color side — the Candidates
    toString oracle (BipartitenessCheckTest.java:40-44). Returns
    ``(False, {})`` on failure, matching fail()'s collapse."""
    if not bool(result.ok):
        return False, {}
    lab = np.asarray(result.labels)
    col = np.asarray(result.colors)
    comps: dict[int, dict[int, bool]] = {}
    slots = np.nonzero(lab >= 0)[0]
    raw = ctx.decode(slots)
    for slot, rid in zip(slots.tolist(), raw.tolist()):
        root_raw = int(ctx.decode(np.array([lab[slot]]))[0])
        comps.setdefault(root_raw, {})[rid] = bool(col[slot] == 0)
    return True, comps
