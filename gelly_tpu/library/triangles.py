"""Triangle counting: windowed, exact streaming, and sampled estimation.

TPU-native re-designs of the reference's three triangle programs:

- :func:`window_triangles` — ``M/example/WindowTriangles.java:48-139``:
  per-window count via wedge candidates matched against window edges. Here
  the candidate-generation/keyBy/match dataflow collapses into one
  vectorized computation per window: an adjacency scatter, an upper-triangle
  wedge mask, and a per-edge common-neighbor reduction (a gather + AND +
  popcount — VPU work instead of the O(deg²) candidate shuffle).

- :func:`exact_triangle_count` — ``M/example/ExactTriangleCount.java:41-207``:
  insertion-only exact local+global counts. The reference waits for both
  endpoints' adjacency snapshots per edge and intersects TreeSets
  (``:74-116``); here a sequential ``lax.scan`` over each chunk intersects
  dense adjacency rows (``adj[u] & adj[v]``) before inserting the edge, so
  every triangle is counted exactly once when its closing edge arrives —
  identical per-edge semantics, one fused device program per chunk.

- :func:`sampled_triangle_count` — the Buriol et al. estimator behind both
  ``BroadcastTriangleCount.java:60-207`` and
  ``IncidenceSamplingTriangleCount.java:23-337``. The reference's per-subtask
  sample states (broadcast) / keyed fan-out (incidence) become a vectorized
  instance axis: all S reservoir states advance in lockstep inside a
  ``lax.scan`` per chunk; sharding that axis over the mesh reproduces the
  incidence-sampling distribution (each device owns S/K instances) with a
  ``psum`` for the global beta sum.
"""

from __future__ import annotations

from functools import partial
from typing import Iterator, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.snapshot import NeighborhoodView
from ..ops import segments

# --------------------------------------------------------------------- #
# windowed


@partial(jax.jit, static_argnames=("capacity", "method"))
def _window_triangle_count(view: NeighborhoodView, capacity: int,
                           method: str = "gather") -> jax.Array:
    """Triangles inside one window's (ALL-direction) sorted view.

    Counts, per unique canonical window edge (a, b), the wedge centers u
    adjacent to both with u < a and u < b — the candidate/match semantics of
    GenerateCandidateEdges + CountTriangles (WindowTriangles.java:82-139):
    each triangle contributes exactly one candidate from its minimum vertex.

    ``method="gather"`` walks per-edge column pairs on the VPU (O(N·E));
    ``method="mxu"``/``"mxu_interpret"`` computes the full wedge matrix
    W = MᵀM with the Pallas MXU kernel (O(N³) but at systolic-array rate —
    the win for dense windows, E ≳ N).
    """
    n = capacity
    key = jnp.where(view.valid, view.key, 0)
    nbr = jnp.where(view.valid, view.nbr, 0)
    adj = jnp.zeros((n, n), bool).at[key, nbr].max(view.valid, mode="drop")
    # wedge mask: M[u, x] = edge(u, x) present with x > u
    cols = jnp.arange(n, dtype=jnp.int32)
    m = adj & (cols[None, :] > cols[:, None])
    # unique canonical edges (a < b), one per undirected window edge
    canon = view.valid & (view.key < view.nbr)
    uniq = segments.unique_pairs_mask(view.key, view.nbr, canon, n)
    if method.startswith("mxu"):
        from ..ops.pallas_kernels import wedge_count_matrix

        w = wedge_count_matrix(m, interpret=method == "mxu_interpret")
        per_edge = w[view.key, view.nbr].astype(jnp.int32)
    else:
        # per-edge common smaller-neighbor count: dot of M columns a and b
        per_edge = jnp.sum(m[:, view.key] & m[:, view.nbr], axis=0)
    return jnp.sum(jnp.where(uniq, per_edge, 0))


def _check_slot_range(capacity: int, full_capacity: int, *arrays_with_mask):
    """Raise when a live slot exceeds a narrowed adjacency capacity —
    scatters would silently drop and gathers clamp otherwise."""
    if capacity >= full_capacity:
        return
    for arr, mask in arrays_with_mask:
        a = np.asarray(arr)
        m = np.asarray(mask)
        hi = int(a[m].max(initial=0))
        if hi >= capacity:
            raise ValueError(
                f"vertex slot {hi} exceeds triangle capacity {capacity}"
            )


def window_triangle_counts_device(stream, window_ms: int,
                                  capacity: int | None = None,
                                  window_capacity: int | None = None,
                                  method: str = "auto") -> Iterator[tuple]:
    """Like :func:`window_triangles` but yields (window, device_scalar)
    WITHOUT host synchronization — counts stay on device so windows
    pipeline. Batch-pull at the end (one D2H round-trip instead of one per
    window; on a tunneled TPU a sync costs ~100ms of fixed latency)."""
    n = capacity if capacity is not None else stream.ctx.vertex_capacity
    snap = stream.slice(window_ms, "all", window_capacity=window_capacity)
    for w, view in snap.views():
        _check_slot_range(
            n, stream.ctx.vertex_capacity,
            (view.key, view.valid), (view.nbr, view.valid),
        )
        m = method
        if m == "auto":
            from ..ops.pallas_kernels import on_tpu

            dense = view.key.shape[0] >= n and n % 128 == 0
            m = "mxu" if (dense and on_tpu()) else "gather"
        yield w, _window_triangle_count(view, n, m)


def window_triangles(stream, window_ms: int, capacity: int | None = None,
                     window_capacity: int | None = None,
                     method: str = "auto") -> Iterator[tuple]:
    """Per-window triangle counts: yields (window_index, count).

    The reference emits (count, window.maxTimestamp) per window
    (WindowTriangles.java:61-65); window_index * window_ms + window_ms - 1
    recovers that timestamp.

    ``method``: "gather" (VPU, sparse windows), "mxu" (Pallas matmul, dense
    windows; needs capacity % 128 == 0), or "auto" (mxu on TPU when the
    window buffer is dense relative to capacity).
    """
    for w, c in window_triangle_counts_device(
        stream, window_ms, capacity, window_capacity, method
    ):
        yield w, int(c)


# --------------------------------------------------------------------- #
# exact streaming


class TriangleCounts(NamedTuple):
    adj: jax.Array  # bool[N, N] inserted edges (undirected)
    counts: jax.Array  # i64[N] per-vertex triangle counters
    total: jax.Array  # i64[] global triangle count


@jax.jit
def _exact_step(state: TriangleCounts, chunk) -> TriangleCounts:
    """Sequential per-edge intersection within the chunk (exact semantics:
    a triangle is counted when its last edge arrives, as in
    IntersectNeighborhoods, ExactTriangleCount.java:74-116)."""

    def step(carry, inp):
        adj, counts, total = carry
        u, v, ok = inp
        fresh = ok & (u != v) & ~adj[u, v]  # duplicate edges are no-ops
        common = adj[u] & adj[v]
        common = jnp.where(fresh, common, jnp.zeros_like(common))
        c = jnp.sum(common.astype(jnp.int64))
        counts = counts + common.astype(jnp.int64)
        counts = counts.at[u].add(jnp.where(fresh, c, 0))
        counts = counts.at[v].add(jnp.where(fresh, c, 0))
        total = total + c
        adj = adj.at[u, v].max(fresh)
        adj = adj.at[v, u].max(fresh)
        return (adj, counts, total), None

    (adj, counts, total), _ = jax.lax.scan(
        step, tuple(state), (chunk.src, chunk.dst, chunk.valid)
    )
    return TriangleCounts(adj, counts, total)


class ExactTriangleStream:
    """Insertion-only exact triangle counts, chunk-grained emission.

    Iterating yields :class:`TriangleCounts` after each chunk; ``final()``
    drains and returns the last. ``final_counts`` renders the reference's
    observable {vertex: count, -1: global} map (SumAndEmitCounters,
    ExactTriangleCount.java:121-134)."""

    def __init__(self, stream, capacity: int | None = None):
        self.stream = stream
        self.capacity = (
            int(capacity) if capacity is not None
            else stream.ctx.vertex_capacity
        )

    def __iter__(self) -> Iterator[TriangleCounts]:
        n = self.capacity
        state = TriangleCounts(
            adj=jnp.zeros((n, n), bool),
            counts=jnp.zeros((n,), jnp.int64),
            total=jnp.zeros((), jnp.int64),
        )
        for c in self.stream:
            _check_slot_range(
                n, self.stream.ctx.vertex_capacity,
                (c.src, c.valid), (c.dst, c.valid),
            )
            state = _exact_step(state, c)
            yield state

    def final(self) -> TriangleCounts:
        if not getattr(self, "_drained", False):
            state = None
            for state in self:
                pass
            if state is None:  # empty stream: allocate the zero state lazily
                n = self.capacity
                state = TriangleCounts(
                    adj=jnp.zeros((n, n), bool),
                    counts=jnp.zeros((n,), jnp.int64),
                    total=jnp.zeros((), jnp.int64),
                )
            self._final = state
            self._drained = True
        return self._final

    def final_counts(self) -> dict[int, int]:
        state = self.final()
        ctx = self.stream.ctx
        out = {-1: int(state.total)}
        counts = np.asarray(state.counts)
        nz = np.nonzero(counts)[0]
        for slot, raw in zip(nz.tolist(), ctx.decode(nz).tolist()):
            out[raw] = int(counts[slot])
        return out


def exact_triangle_count(stream, capacity: int | None = None) -> ExactTriangleStream:
    return ExactTriangleStream(stream, capacity)


# --------------------------------------------------------------------- #
# sampled estimation


class SamplerState(NamedTuple):
    src: jax.Array  # i32[S] sampled edge endpoints
    trg: jax.Array
    third: jax.Array  # i32[S] sampled third vertex
    src_found: jax.Array  # bool[S]
    trg_found: jax.Array  # bool[S]
    edge_count: jax.Array  # i32[] edges seen
    key: jax.Array  # PRNG key


def _fresh_sampler(num_samples: int, seed: int) -> SamplerState:
    s = num_samples
    return SamplerState(
        src=jnp.full((s,), -1, jnp.int32),
        trg=jnp.full((s,), -1, jnp.int32),
        third=jnp.full((s,), -1, jnp.int32),
        src_found=jnp.zeros((s,), bool),
        trg_found=jnp.zeros((s,), bool),
        edge_count=jnp.zeros((), jnp.int32),
        key=jax.random.PRNGKey(seed),
    )


@partial(jax.jit, static_argnames=("num_vertices",))
def _sampler_step(state: SamplerState, chunk, num_vertices: int) -> SamplerState:
    """Advance all S reservoir instances over the chunk's edges in stream
    order (TriangleSampler.flatMap, BroadcastTriangleCount.java:79-126)."""

    def step(st, inp):
        u, v, ok = inp
        i = st.edge_count + 1  # 1-based edge index
        key, k1, k2 = jax.random.split(st.key, 3)
        s = st.src.shape[0]
        # Coin.flip: resample this instance's edge with probability 1/i.
        coin = (
            jax.random.uniform(k1, (s,)) * i.astype(jnp.float32) < 1.0
        ) & ok
        # Third vertex uniform over V \ {u, v}: draw from [0, V-2) and
        # shift past both excluded endpoints in ascending order.
        a = jnp.minimum(u, v)
        b = jnp.maximum(u, v)
        cand = jax.random.randint(k2, (s,), 0, num_vertices - 2, jnp.int32)
        cand = cand + (cand >= a).astype(jnp.int32)
        cand = cand + (cand >= b).astype(jnp.int32)
        src = jnp.where(coin, u, st.src)
        trg = jnp.where(coin, v, st.trg)
        third = jnp.where(coin, cand, st.third)
        src_found = jnp.where(coin, False, st.src_found)
        trg_found = jnp.where(coin, False, st.trg_found)
        # Match the two remaining wedge edges against this edge.
        m_src = ((u == src) & (v == third)) | ((u == third) & (v == src))
        m_trg = ((u == trg) & (v == third)) | ((u == third) & (v == trg))
        src_found = src_found | (m_src & ok)
        trg_found = trg_found | (m_trg & ok)
        return SamplerState(
            src, trg, third, src_found, trg_found,
            st.edge_count + ok.astype(jnp.int32), key,
        ), None

    out, _ = jax.lax.scan(step, state, (chunk.src, chunk.dst, chunk.valid))
    return out


def sampler_estimate(state: SamplerState, num_vertices: int) -> float:
    """(1/S) * beta_sum * edge_count * (V - 2) — TriangleSummer's scaling
    (BroadcastTriangleCount.java:158-166)."""
    beta = jnp.sum((state.src_found & state.trg_found).astype(jnp.float32))
    s = state.src.shape[0]
    return float(
        beta / s * state.edge_count.astype(jnp.float32) * (num_vertices - 2)
    )


def sampled_triangle_count(stream, num_samples: int,
                           num_vertices: int | None = None,
                           seed: int = 0xDEADBEEF) -> Iterator[float]:
    """Streaming estimate, one value per chunk. ``seed`` defaults to the
    incidence example's seeded RNG (IncidenceSamplingTriangleCount.java:78)
    for reproducibility."""
    v = num_vertices if num_vertices is not None else stream.ctx.vertex_capacity
    state = _fresh_sampler(num_samples, seed)
    for c in stream:
        state = _sampler_step(state, c, v)
        yield sampler_estimate(state, v)
