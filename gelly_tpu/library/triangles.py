"""Triangle counting: windowed, exact streaming, and sampled estimation.

TPU-native re-designs of the reference's three triangle programs:

- :func:`window_triangles` — ``M/example/WindowTriangles.java:48-139``:
  per-window count via wedge candidates matched against window edges. Here
  the candidate-generation/keyBy/match dataflow collapses into one
  vectorized computation per window: an adjacency scatter, an upper-triangle
  wedge mask, and a per-edge common-neighbor reduction (a gather + AND +
  popcount — VPU work instead of the O(deg²) candidate shuffle).

- :func:`exact_triangle_count` — ``M/example/ExactTriangleCount.java:41-207``:
  insertion-only exact local+global counts with exact per-edge closing
  semantics. The reference waits for both endpoints' adjacency snapshots
  per edge and intersects TreeSets (``:74-116``); here the adjacency
  stores each edge's *arrival index* and whole slabs of edges intersect at
  once as masked row ops — a triangle is attributed to the edge whose
  index is largest, i.e. exactly when its closing edge arrives, with no
  per-edge scan. A capped-degree sparse table (O(N·D) memory) covers
  N ≥ 1M; the dense matrix is the small-N fast path.

- :func:`sampled_triangle_count` — the Buriol et al. estimator behind both
  ``BroadcastTriangleCount.java:60-207`` and
  ``IncidenceSamplingTriangleCount.java:23-337``. The reference's per-subtask
  sample states (broadcast) / keyed fan-out (incidence) become a vectorized
  instance axis: all S reservoir states advance in lockstep inside a
  ``lax.scan`` per chunk; sharding that axis over the mesh reproduces the
  incidence-sampling distribution (each device owns S/K instances) with a
  ``psum`` for the global beta sum.
"""

from __future__ import annotations

from functools import partial
from typing import Iterator, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.snapshot import NeighborhoodView
from ..ops import segments

# --------------------------------------------------------------------- #
# windowed


def _wedge_count_from_adj(adj: jax.Array, key: jax.Array, nbr: jax.Array,
                          valid: jax.Array, n: int,
                          method: str = "gather") -> jax.Array:
    """Count triangles from a window adjacency + its (key, nbr) edge list.

    Per unique canonical edge (a, b), counts wedge centers u adjacent to
    both with u < a and u < b — the candidate/match semantics of
    GenerateCandidateEdges + CountTriangles (WindowTriangles.java:82-139):
    each triangle contributes exactly one candidate from its minimum
    vertex. Shared by the single-device kernel (local adjacency) and the
    mesh kernel (psum-assembled global adjacency).
    """
    # wedge mask: M[u, x] = edge(u, x) present with x > u
    cols = jnp.arange(n, dtype=jnp.int32)
    m = adj & (cols[None, :] > cols[:, None])
    # unique canonical edges (a < b), one per undirected window edge
    canon = valid & (key < nbr)
    uniq = segments.unique_pairs_mask(key, nbr, canon, n)
    if method.startswith("mxu"):
        from ..ops.pallas_kernels import wedge_count_matrix

        w = wedge_count_matrix(
            m,
            # explicit interpret only when forced; None = auto
            # (compiled on TPU, interpreter on the CPU mesh)
            interpret=True if method == "mxu_interpret" else None,
        )
        per_edge = w[key, nbr].astype(jnp.int32)
    else:
        # per-edge common smaller-neighbor count: dot of M columns a and b
        per_edge = jnp.sum(m[:, key] & m[:, nbr], axis=0)
    return jnp.sum(jnp.where(uniq, per_edge, 0))


@partial(jax.jit, static_argnames=("capacity", "method"))
def _window_triangle_count(view: NeighborhoodView, capacity: int,
                           method: str = "gather") -> jax.Array:
    """Triangles inside one window's (ALL-direction) sorted view.

    ``method="gather"`` walks per-edge column pairs on the VPU (O(N·E));
    ``method="mxu"``/``"mxu_interpret"`` computes the full wedge matrix
    W = MᵀM with the Pallas MXU kernel (O(N³) but at systolic-array rate —
    the win for dense windows, E ≳ N). Counting semantics in
    :func:`_wedge_count_from_adj`.
    """
    n = capacity
    key = jnp.where(view.valid, view.key, 0)
    nbr = jnp.where(view.valid, view.nbr, 0)
    adj = jnp.zeros((n, n), bool).at[key, nbr].max(view.valid, mode="drop")
    return _wedge_count_from_adj(
        adj, view.key, view.nbr, view.valid, n, method
    )


def _needs_rebase(seen_host: int, chunk, budget: int) -> bool:
    """Arrival indices are i32: rebase the summary before they can wrap
    (a wrapped index would silently invert the closing-edge comparison).

    The rebase is LOSSLESS: stored indices are only ever compared against
    the arrival index of a *later* edge (the closing-edge attribution
    rule; stored entries are never compared to each other — duplicates
    dedup before insertion), so collapsing every present entry to -1 and
    resetting ``n_seen`` to 0 preserves all future comparisons exactly
    while freeing the whole i32 range for the next ~2^31 arrivals.
    ``budget`` is INT_MAX in production; tests shrink it to exercise the
    rebase without streaming 2^31 edges.
    """
    return seen_host + int(np.asarray(chunk.valid).sum()) >= (
        budget - chunk.capacity
    )


@jax.jit
def _rebase_dense(state: "TriangleCounts") -> "TriangleCounts":
    adj = jnp.where(
        state.adj != segments.INT_MAX, -1, segments.INT_MAX
    ).astype(jnp.int32)
    return state._replace(adj=adj, n_seen=jnp.zeros((), jnp.int32))


@jax.jit
def _rebase_sparse(state: "SparseTriangleCounts") -> "SparseTriangleCounts":
    aidx = jnp.where(
        state.aidx != segments.INT_MAX, -1, segments.INT_MAX
    ).astype(jnp.int32)
    return state._replace(aidx=aidx, n_seen=jnp.zeros((), jnp.int32))


def _check_slot_range(capacity: int, full_capacity: int, *arrays_with_mask):
    """Raise when a live slot exceeds a narrowed adjacency capacity —
    scatters would silently drop and gathers clamp otherwise."""
    if capacity >= full_capacity:
        return
    for arr, mask in arrays_with_mask:
        a = np.asarray(arr)
        m = np.asarray(mask)
        hi = int(a[m].max(initial=0))
        if hi >= capacity:
            raise ValueError(
                f"vertex slot {hi} exceeds triangle capacity {capacity}"
            )


@partial(jax.jit, static_argnames=("n", "capacity", "method"))
def _window_triangle_count_packed(packed: jax.Array, n: int, capacity: int,
                                  method: str) -> jax.Array:
    """Packed-wire variant: ``packed[i] = a*n + b`` with ``a < b`` — the
    window's UNIQUE canonical undirected edges (host-deduped, self-loops
    removed), INT_MAX padding.

    The H2D transfer is the dominant window cost on a bandwidth-limited
    link, so the wire carries exactly one i32 lane per undirected window
    edge; the ALL-direction adjacency is rebuilt on device (both
    directions share the edge's timestamp window, so symmetrizing after
    the transfer is exact). Host dedup also removes every device-side
    sort/first-occurrence pass: per-edge counting runs on exactly one
    canonical lane per edge (the GenerateCandidateEdges wedge-center
    semantics of :func:`_wedge_count_from_adj`, with the canon/uniq masks
    statically true).
    """
    valid = packed != segments.INT_MAX
    safe = jnp.where(valid, packed, 0)
    a = (safe // n).astype(jnp.int32)
    b = (safe % n).astype(jnp.int32)
    adj = jnp.zeros((capacity, capacity), bool)
    adj = adj.at[a, b].max(valid, mode="drop")
    adj = adj.at[b, a].max(valid, mode="drop")
    cols = jnp.arange(capacity, dtype=jnp.int32)
    m = adj & (cols[None, :] > cols[:, None])
    if method.startswith("mxu"):
        from ..ops.pallas_kernels import wedge_count_matrix

        w = wedge_count_matrix(
            m,
            # explicit interpret only when forced; None = auto
            # (compiled on TPU, interpreter on the CPU mesh)
            interpret=True if method == "mxu_interpret" else None,
        )
        per_edge = w[a, b].astype(jnp.int32)
    else:
        per_edge = jnp.sum(m[:, a] & m[:, b], axis=0)
    return jnp.sum(jnp.where(valid, per_edge, 0))


@partial(jax.jit, static_argnames=("n", "max_degree", "slab"))
def _window_triangle_count_sparse(key: jax.Array, nbr: jax.Array,
                                  valid: jax.Array, n: int,
                                  max_degree: int,
                                  slab: int | None = None):
    """Window triangle count over a capped-degree row table — the large-N
    path (the dense kernel's ``bool[N, N]`` adjacency is infeasible past
    N ~ 46k, where the packed wire format also stops fitting i32).

    Input is the single-copy OUT-direction window (key, nbr, valid);
    the doubled view is built in-kernel. The window's (deduped) adjacency
    is scattered into ``i32[N, D]`` neighbor rows (ranks from a sorted
    segment scan), and each canonical edge (a < b) counts common
    neighbors u < a by a slab-mapped D x D row intersection — same
    candidate/match semantics as the dense kernel
    (WindowTriangles.java:82-139).

    Returns ``(count i64, overflow i32)`` — overflow is the number of
    adjacency entries dropped by the degree cap; the caller must treat
    any overflow as an error (a dropped entry could hide triangles).
    """
    D = max_degree
    if slab is None:
        # Bound the [slab, D, D] intersection tensor (same sizing rule as
        # the sparse exact stream).
        slab = max(8, (1 << 22) // max(1, D * D))
    k2 = jnp.concatenate([key, nbr])
    n2 = jnp.concatenate([nbr, key])
    ok = jnp.concatenate([valid, valid]) & (k2 != n2)
    # Sort by (key, nbr): duplicates become adjacent, rows fill ascending.
    pack = jnp.where(
        ok, k2.astype(jnp.int64) * n + n2.astype(jnp.int64),
        jnp.iinfo(jnp.int64).max,
    )
    order = jnp.argsort(pack)
    sk, sn, so, sp = k2[order], n2[order], ok[order], pack[order]
    fresh = segments.segment_starts(sp, so)  # drop duplicate directed pairs
    run = segments.segment_starts(
        jnp.where(so, sk, segments.INT_MAX), so
    )
    # Rank among fresh entries within each key run: cumulative fresh count
    # minus the run's base, propagated from the run start (cumsum is
    # monotone, so a running max carries the latest run's base forward).
    cf = jnp.cumsum(fresh.astype(jnp.int32))
    base = jax.lax.associative_scan(
        jnp.maximum, jnp.where(run, cf - fresh.astype(jnp.int32), 0)
    )
    rank = cf - fresh.astype(jnp.int32) - base
    fits = fresh & (rank < D)
    overflow = jnp.sum((fresh & ~fits).astype(jnp.int32))
    table = jnp.full((n, D), -1, jnp.int32)
    table = table.at[
        jnp.where(fits, sk, n), jnp.minimum(rank, D - 1)
    ].set(sn, mode="drop")

    # One canonical lane per undirected window edge.
    canon = fresh & (sk < sn)
    L2 = sk.shape[0]
    pad = (-L2) % slab
    csk = jnp.pad(sk, (0, pad))
    csn = jnp.pad(sn, (0, pad))
    cok = jnp.pad(canon, (0, pad))
    S = csk.shape[0] // slab

    def body(args):
        a_id, b_id, live = args  # [slab] each
        rows_a = table[jnp.where(live, a_id, 0)]  # [slab, D]
        rows_b = table[jnp.where(live, b_id, 0)]
        m = (
            (rows_a[:, :, None] == rows_b[:, None, :])
            & (rows_a[:, :, None] >= 0)
            # wedge-min convention: count centers u < a = min(a, b)
            & (rows_a[:, :, None] < a_id[:, None, None])
        )
        per = jnp.sum(m, axis=(1, 2))
        return jnp.sum(jnp.where(live, per, 0).astype(jnp.int64))

    counts = jax.lax.map(body, (
        csk.reshape(S, slab), csn.reshape(S, slab), cok.reshape(S, slab)
    ))
    return jnp.sum(counts), overflow


DENSE_ROW_CAP = 64  # fill above this makes a row "hot" (bitmap path)


def _ladder(d: int) -> tuple[int, ...]:
    """Power-of-two degree buckets 4, 8, ..., d (shared by the window
    bucketizer and the stacker — one definition, or per-window buckets
    silently misalign with the group ladder)."""
    out = []
    db = 4
    while True:
        out.append(min(db, d))
        if db >= d:
            break
        db *= 2
    return tuple(out)


def _pow2_cap(longest: int, floor: int) -> int:
    """Smallest power of two >= max(longest, 1), floored."""
    return max(floor, 1 << max(0, longest - 1).bit_length())


def _in_groups(it, batch: int):
    g: list = []
    for item in it:
        g.append(item)
        if len(g) == batch:
            yield g
            g = []
    if g:
        yield g


def _slab_map(body, arrays, slab: int, pads) -> jax.Array:
    """Pad 1-D arrays to a slab multiple and lax.map ``body`` over
    [slab]-shaped pieces; returns the i64 sum of the per-slab results.
    ``pads`` gives each array's padding value (the first array's padding
    must make padded lanes invalid for ``body``)."""
    e = arrays[0].shape[0]
    pad = (-e) % slab
    padded = tuple(
        jnp.pad(x, (0, pad), constant_values=v)
        for x, v in zip(arrays, pads)
    )
    s = padded[0].shape[0] // slab
    return jnp.sum(jax.lax.map(
        body, tuple(x.reshape((s, slab) + x.shape[1:]) for x in padded)
    ))


def _bucketize_window(bk: np.ndarray, bn: np.ndarray, bo: np.ndarray,
                      n: int, max_degree: int | None) -> dict:
    """Host-side window prep for the bucketed sparse count (numpy, runs on
    the ingest/prefetch side): dedup directed pairs, build the COMPACT row
    table layout (row ids over touched vertices only), split canonical
    edges into power-of-two degree buckets by ACTUAL row fill, and carve
    out the SKEW SPLIT — rows with fill > :data:`DENSE_ROW_CAP` become
    per-window BITMAPS over the compact row space instead of D-capped
    rows, so a Zipf hot vertex costs its edges O(fill_sparse) membership
    gathers (hot-sparse) or O(T) bitmap ANDs (hot-hot) instead of a
    ``max_fill^2`` intersection.

    This moves the old sparse kernel's per-window device i64 argsort +
    rank scan (~200ms/window on a v5e for 2^19 lanes — the dominant cost)
    to a ~10-30ms numpy pass that pipelines with device work.

    With ``max_degree=None`` (default) nothing can overflow — hot rows
    have no depth cap at all; an explicit cap bounds the HOT row fill and
    raises HERE, before any count is produced, so yielded counts are
    always exact (the deferred-overflow contract of the older sparse path
    is gone).
    """
    k = bk[bo].astype(np.int64)
    m = bn[bo].astype(np.int64)
    k2 = np.concatenate([k, m])
    n2 = np.concatenate([m, k])
    keep = k2 != n2  # self-loops close no triangles
    pack = np.unique(k2[keep] * n + n2[keep])
    a = (pack // n).astype(np.int32)
    b = (pack % n).astype(np.int32)
    rows, inv, fill = np.unique(a, return_inverse=True, return_counts=True)
    max_fill = int(fill.max()) if fill.size else 1
    if max_degree is not None and max_fill > max_degree:
        raise ValueError(
            f"window adjacency row fill {max_fill} exceeds "
            f"max_degree={max_degree}; raise max_degree or drop the cap "
            "(the bucketed path raises before yielding, so no corrupt "
            "count escapes; hot rows go to the bitmap path regardless)"
        )
    d = 1 << max(2, (min(max_fill, DENSE_ROW_CAP) - 1).bit_length())
    starts = np.searchsorted(a, rows)
    rank = (np.arange(a.shape[0]) - starts[inv]).astype(np.int32)
    inv32 = inv.astype(np.int32)
    ridb = np.searchsorted(rows, b).astype(np.int32)  # rid of each nbr

    hot_row = fill > DENSE_ROW_CAP
    hot_rows = np.nonzero(hot_row)[0].astype(np.int32)
    hidx_of = np.full(rows.shape[0], -1, np.int32)
    hidx_of[hot_rows] = np.arange(hot_rows.shape[0], dtype=np.int32)

    # Table entries: non-hot rows only (hot rows live in the bitmap).
    in_table = ~hot_row[inv] & (rank < d)
    pos = np.where(in_table, inv32 * d + rank, -1).astype(np.int32)
    # Bitmap entries: directed pairs whose source row is hot.
    bm = hot_row[inv]
    bh = hidx_of[inv32[bm]]
    brid = ridb[bm]

    c = a < b  # one canonical lane per undirected edge
    ra = inv32[c]
    rb = ridb[c]
    av = a[c]
    a_hot = hot_row[ra]
    b_hot = hot_row[rb]
    hh = a_hot & b_hot
    hs = a_hot ^ b_hot
    ss = ~(a_hot | b_hot)
    ladder = _ladder(d)
    prev = 0
    buckets = []
    need = np.maximum(fill[ra], fill[rb])
    for db in ladder:
        sel = ss & (need > prev) & (need <= db)
        buckets.append((ra[sel], rb[sel], av[sel]))
        prev = db
    # Hot-sparse: iterate the SPARSE side's row, test membership in the
    # hot side's bitmap; hot-hot: AND the two bitmaps over the row space.
    h_side = np.where(a_hot, ra, rb)[hs]
    s_side = np.where(a_hot, rb, ra)[hs]
    return {
        "pos": pos, "nbr": b, "rid": ridb, "t": rows.shape[0], "d": d,
        "ladder": ladder, "buckets": buckets,
        "rows": rows.astype(np.int32),
        "n_hot": hot_rows.shape[0], "bh": bh, "brid": brid,
        "hs": (hidx_of[h_side], s_side, av[hs]),
        "hh": (hidx_of[ra[hh]], hidx_of[rb[hh]], av[hh]),
    }


def _stack_bucketed(group: list[dict]) -> tuple:
    """Pad + stack K windows' bucketed payloads to shared pow-2 caps.

    Shared caps: table depth d and ladder take the group max (a window
    with smaller d still counts correctly — its rows simply leave the
    upper lanes empty); per-bucket/bitmap/edge caps are pow-2 of the
    group max, so the jitted kernel sees O(log) distinct shapes.
    """
    d = max(p["d"] for p in group)
    ladder = _ladder(d)
    t_cap = _pow2_cap(max(p["t"] for p in group), 64)
    p_cap = _pow2_cap(max(p["pos"].shape[0] for p in group), 64)
    h_cap = _pow2_cap(max(p["n_hot"] for p in group), 1)
    b_cap = _pow2_cap(max(p["bh"].shape[0] for p in group), 8)

    def pad_to(x, cap, fillv):
        out = np.full((cap,), fillv, np.int32)
        out[: x.shape[0]] = x
        return out

    pos_k, nbr_k, rid_k, val_k, bpos_k = [], [], [], [], []
    for p in group:
        # Re-express pos in the SHARED depth d (row*d + rank).
        live = p["pos"] >= 0
        rows_p = np.where(live, p["pos"] // p["d"], 0)
        rank_p = np.where(live, p["pos"] % p["d"], 0)
        pos_k.append(pad_to(
            np.where(live, rows_p * d + rank_p, -1), p_cap, -1
        ))
        nbr_k.append(pad_to(p["nbr"], p_cap, 0))
        rid_k.append(pad_to(p["rid"], p_cap, 0))
        val_k.append(pad_to(p["rows"], t_cap, segments.INT_MAX))
        bpos_k.append(pad_to(p["bh"] * t_cap + p["brid"], b_cap, -1))
    stacked_buckets = []
    for bi, db in enumerate(ladder):
        e_cap = _pow2_cap(
            max(
                (p["buckets"][bi][0].shape[0]
                 if bi < len(p["buckets"]) else 0)
                for p in group
            ), 8,
        )
        ras, rbs, avs = [], [], []
        for p in group:
            if bi < len(p["buckets"]):
                ra, rb, av = p["buckets"][bi]
            else:
                ra = rb = av = np.empty(0, np.int32)
            ras.append(pad_to(ra, e_cap, -1))
            rbs.append(pad_to(rb, e_cap, 0))
            avs.append(pad_to(av, e_cap, 0))
        stacked_buckets.append(
            (np.stack(ras), np.stack(rbs), np.stack(avs))
        )

    def stack_cls(key):
        e_cap = _pow2_cap(max(p[key][0].shape[0] for p in group), 8)
        return tuple(
            np.stack([pad_to(p[key][j], e_cap, fv) for p in group])
            for j, fv in ((0, -1), (1, 0), (2, 0))
        )

    return (
        {
            "pos": np.stack(pos_k), "nbr": np.stack(nbr_k),
            "rid": np.stack(rid_k), "val": np.stack(val_k),
            "bpos": np.stack(bpos_k),
            "buckets": tuple(stacked_buckets),
            "hs": stack_cls("hs"), "hh": stack_cls("hh"),
        },
        t_cap, d, h_cap, tuple(ladder),
    )


@partial(jax.jit, static_argnames=("t_cap", "d", "h_cap", "ladder"))
def _window_triangle_count_bucketed_group(payload, t_cap, d, h_cap, ladder):
    """i64[K] counts for K stacked bucketized windows (one dispatch).

    Per window: scatter the compact row table (no sort — ranks came from
    the host) + the hot-row bitmap, then three edge classes:

    - sparse-sparse: [E_b, db, db] row intersections per degree bucket,
      slab-mapped (db ≤ DENSE_ROW_CAP);
    - hot-sparse: iterate the sparse side's row (≤ DENSE_ROW_CAP entries)
      and test membership in the hot side's bitmap — O(fill_sparse)/edge;
    - hot-hot: AND the two bitmaps over the compact row space —
      O(T)/edge, slab-mapped.

    Same candidate/match semantics as the dense kernel
    (WindowTriangles.java:82-139): centers u < a = min(a, b)."""

    def one(p):
        pos, nbr, rid, val, bpos = (
            p["pos"], p["nbr"], p["rid"], p["val"], p["bpos"]
        )
        okp = pos >= 0
        table = jnp.full((t_cap * d,), -1, jnp.int32).at[
            jnp.where(okp, pos, t_cap * d)
        ].set(nbr, mode="drop").reshape(t_cap, d)
        table_rid = jnp.full((t_cap * d,), 0, jnp.int32).at[
            jnp.where(okp, pos, t_cap * d)
        ].set(rid, mode="drop").reshape(t_cap, d)
        okb = bpos >= 0
        bitmap = jnp.zeros((h_cap * t_cap,), bool).at[
            jnp.where(okb, bpos, h_cap * t_cap)
        ].set(True, mode="drop")
        total = jnp.int64(0)
        for db, (ra, rb, av) in zip(ladder, p["buckets"]):

            def ss_body(args2, db=db):
                ra_s, rb_s, av_s = args2
                ok_s = ra_s >= 0
                rows_a = table[jnp.where(ok_s, ra_s, 0)][:, :db]
                rows_b = table[jnp.where(ok_s, rb_s, 0)][:, :db]
                mt = (
                    (rows_a[:, :, None] == rows_b[:, None, :])
                    & (rows_a[:, :, None] >= 0)
                    & (rows_a[:, :, None] < av_s[:, None, None])
                )
                per = jnp.sum(mt, axis=(1, 2))
                return jnp.sum(
                    jnp.where(ok_s, per, 0).astype(jnp.int64)
                )

            total += _slab_map(
                ss_body, (ra, rb, av),
                max(8, (1 << 22) // (db * db)), (-1, 0, 0),
            )

        # Hot-sparse: membership gathers from the hot bitmap — slab-mapped
        # like the other classes (a full [E, d] gather would spike
        # transient memory ∝ the hot-sparse edge cap).
        def hs_body(args2):
            h_s, srow_s, av_s = args2
            ok_s = h_s >= 0
            vals = table[jnp.where(ok_s, srow_s, 0)]  # [slab, d]
            rids = table_rid[jnp.where(ok_s, srow_s, 0)]
            member = bitmap[jnp.where(ok_s, h_s, 0)[:, None] * t_cap + rids]
            mt = member & (vals >= 0) & (vals < av_s[:, None])
            return jnp.sum(
                jnp.where(ok_s, jnp.sum(mt, axis=1), 0).astype(jnp.int64)
            )

        total += _slab_map(
            hs_body, p["hs"], max(8, (1 << 22) // d), (-1, 0, 0)
        )

        # Hot-hot: bitmap AND over the compact row space, slab-mapped.
        bm2 = bitmap.reshape(h_cap, t_cap)

        def hh_body(args2):
            ha_s, hb_s, av_s = args2
            ok_s = ha_s >= 0
            ma = bm2[jnp.where(ok_s, ha_s, 0)]
            mb = bm2[jnp.where(ok_s, hb_s, 0)]
            mt = ma & mb & (val[None, :] < av_s[:, None])
            per = jnp.sum(mt, axis=1)
            return jnp.sum(jnp.where(ok_s, per, 0).astype(jnp.int64))

        total += _slab_map(
            hh_body, p["hh"], max(4, (1 << 22) // t_cap), (-1, 0, 0)
        )
        return total

    return jax.lax.map(one, payload)


def window_triangles_bucketed(stream, window_ms: int,
                              capacity: int | None = None,
                              window_capacity: int | None = None,
                              max_degree: int | None = None,
                              batch: int = 8) -> Iterator[tuple]:
    """Per-window triangle counts on the degree-bucketed sparse path — the
    large-N workhorse (VERDICT r3 item 4): host-side dedup/rank/bucketize
    (pipelines with device work), compact row table ∝ touched vertices,
    and D x D intersections sized by each edge's ACTUAL row fill.

    Yields ``(window, count device scalar)`` in groups of up to ``batch``
    windows per dispatch. ``max_degree=None`` (default) adapts the table
    depth to each window's true max degree — no overflow possible; an
    explicit cap raises on the host BEFORE any count is yielded.

    Semantics: ``WindowTriangles.java:82-139`` (candidate wedges joined
    against real edges per tumbling window), validated against the dense
    kernel in tests on duplicate/self-loop/reversed streams.
    """
    n = capacity if capacity is not None else stream.ctx.vertex_capacity

    from ..utils.prefetch import prefetch_map

    def stage(group):
        wins = [w for w, _ in group]
        payloads = [
            _bucketize_window(bk, bn, bo, n, max_degree)
            for _, (bk, bn, bo) in group
        ]
        payload, t_cap, d, h_cap, ladder = _stack_bucketed(payloads)
        return wins, (jax.tree.map(jnp.asarray, payload),
                      t_cap, d, h_cap, ladder)

    for wins, (payload, t_cap, d, h_cap, ladder) in prefetch_map(
        stage,
        _in_groups(_out_windows(stream, window_ms, window_capacity, n),
                   batch),
        depth=2, workers=1,
    ):
        counts = _window_triangle_count_bucketed_group(
            payload, t_cap, d, h_cap, ladder
        )
        yield from zip(wins, (counts[i] for i in range(len(wins))))


def _pick_method(method: str, n: int):
    """Resolve method="auto" per window: MXU for dense windows on TPU."""
    if method != "auto":
        return lambda view_len: method
    from ..ops.pallas_kernels import on_tpu

    tpu = on_tpu()
    return lambda view_len: (
        "mxu" if (view_len >= n and n % 128 == 0 and tpu) else "gather"
    )


def _out_windows(stream, window_ms: int, window_capacity: int | None,
                 n: int) -> Iterator[tuple[int, tuple]]:
    """(window, (key, nbr, valid) host columns) per closed window.

    OUT-direction windows carry each edge once; the doubled ALL-direction
    view the count kernels expect is rebuilt on device (mirror) — both
    directions share the edge's timestamp window, so symmetrizing after
    the transfer is exact and ships half the bytes of the undirected
    window buffer. ``window_capacity`` is calibrated by callers for the
    doubled ALL-direction buffer; the single-copy buffer needs half of
    it. Unsorted (the count kernels are order-independent).
    """
    snap = stream.slice(
        window_ms, "out",
        window_capacity=None if window_capacity is None
        else max(1, window_capacity // 2),
    )
    try:
        for w, (bk, bn, _bv, bo) in snap.host_buffers(sort=False):
            _check_slot_range(n, stream.ctx.vertex_capacity,
                              (bk, bo), (bn, bo))
            yield w, (bk, bn, bo)
    except ValueError as e:
        if "window buffer overflow" in str(e):
            raise ValueError(
                f"{e} — note: the triangle paths store each window "
                "edge once and size their buffer as window_capacity // 2 "
                "(window_capacity keeps the ALL-direction doubled-buffer "
                "calibration)"
            ) from e
        raise


def _packed_out_windows(stream, window_ms: int, window_capacity: int | None,
                        n: int) -> Iterator[tuple[int, np.ndarray]]:
    """(window, packed i32 host column): ``key*n + nbr`` of the window's
    UNIQUE directed edges, ascending, no padding (requires n^2 < 2^31).

    Deduping on the host (np.unique) before the transfer is the wire win:
    the count kernel only needs each directed edge once, and real streams
    repeat hot pairs heavily (the bench's Zipf windows carry ~3x
    duplicates), so the shipped column is ∝ unique edges instead of the
    padded window capacity. Callers bucket-pad per dispatch group."""
    for w, (bk, bn, bo) in _out_windows(stream, window_ms,
                                        window_capacity, n):
        a = np.minimum(bk[bo], bn[bo]).astype(np.int64)
        b = np.maximum(bk[bo], bn[bo]).astype(np.int64)
        keep = a != b  # self-loops close no triangles
        yield w, np.unique(a[keep] * n + b[keep]).astype(np.int32)


def window_triangle_counts_device(stream, window_ms: int,
                                  capacity: int | None = None,
                                  window_capacity: int | None = None,
                                  method: str = "auto") -> Iterator[tuple]:
    """Like :func:`window_triangles` but yields (window, device_scalar)
    WITHOUT host synchronization — counts stay on device so windows
    pipeline. Batch-pull at the end (one D2H round-trip instead of one per
    window; on a tunneled TPU a sync costs ~100ms of fixed latency).

    When the slot space fits (capacity^2 < 2^31) the window view ships as
    ONE packed i32 column per single-copy window edge instead of
    key/nbr/val/valid — ~6x fewer wire bytes for the dominant per-window
    transfer (see :func:`_packed_out_windows`).
    """
    n = capacity if capacity is not None else stream.ctx.vertex_capacity

    if n * n < (1 << 31):
        # The per-window path is the batch=1 degenerate of the grouped one
        # (no added emission latency).
        yield from window_triangle_counts_batched(
            stream, window_ms, capacity, window_capacity, method, batch=1
        )
        return
    pick = _pick_method(method, n)
    snap = stream.slice(window_ms, "all", window_capacity=window_capacity)
    for w, view in snap.views():
        _check_slot_range(
            n, stream.ctx.vertex_capacity,
            (view.key, view.valid), (view.nbr, view.valid),
        )
        yield w, _window_triangle_count(view, n, pick(view.key.shape[0]))


@partial(jax.jit, static_argnames=("n", "capacity", "method"))
def _window_triangle_count_packed_group(packed_kl: jax.Array, n: int,
                                        capacity: int, method: str
                                        ) -> jax.Array:
    """Count triangles for a GROUP of packed windows in one dispatch.

    ``packed_kl`` is ``i32[K, L]`` — K canonical-unique window columns
    stacked on the host. ``lax.map`` runs the per-window count sequentially
    on device, so HBM holds one window's dense state at a time while the
    host pays one transfer + one dispatch for the whole group (the same
    fixed-cost amortization as the engine's ``fold_batch``).
    """
    return jax.lax.map(
        lambda p: _window_triangle_count_packed(p, n, capacity, method),
        packed_kl,
    )


@partial(jax.jit, static_argnames=("n", "max_degree"))
def _window_triangle_count_sparse_group(keys_kl, nbrs_kl, valids_kl,
                                        n: int, max_degree: int):
    """(counts i64[K], overflows i32[K]) for K stacked sparse windows."""
    return jax.lax.map(
        lambda t: _window_triangle_count_sparse(
            t[0], t[1], t[2], n, max_degree
        ),
        (keys_kl, nbrs_kl, valids_kl),
    )


def window_triangle_counts_batched(stream, window_ms: int,
                                   capacity: int | None = None,
                                   window_capacity: int | None = None,
                                   method: str = "auto",
                                   batch: int = 4,
                                   max_degree: int | None = None,
                                   yield_overflow: bool = False
                                   ) -> Iterator[tuple]:
    """Per-window counts with up to ``batch`` closed windows per device
    dispatch: yields (window_index, device_scalar) like
    :func:`window_triangle_counts_device` but amortizes the per-transfer
    fixed cost over the group — the window-path analog of the engine's
    ``fold_batch`` (emission latency grows by up to ``batch - 1`` windows;
    the final partial group dispatches at its own smaller size).

    ``max_degree`` selects the capped-degree sparse kernel
    (:func:`_window_triangle_count_sparse`) — the ONLY path for large
    vertex capacities (the dense kernel's bool[N, N] adjacency and the
    packed i32 wire format both stop at N ~ 46k). Degree-cap overflow
    raises (a dropped adjacency entry could hide triangles; raise
    ``max_degree`` to the window's true max degree). The overflow check is
    deferred by one group to preserve pipelining, so up to ``batch`` counts
    from the overflowing group may be yielded (corrupt) before the raise —
    consumers acting per yield must not treat yielded counts as final until
    the next iteration step (or ``StopIteration``) succeeds. Alternatively
    ``yield_overflow=True`` yields ``(window, count, overflow)`` triples on
    this path (``overflow`` = that window's device scalar of dropped
    adjacency entries): pulling it syncs the host, so per-yield gating
    costs the pipelining the default defers for — but lets a consumer
    reject exactly the corrupt windows programmatically instead of
    trusting iterator progress.

    Without ``max_degree``, capacities with capacity^2 >= 2^31 degrade to
    the unpacked dense per-window path — one transfer and dispatch per
    window, no grouping, and infeasible memory past N ~ 46k.
    """
    n = capacity if capacity is not None else stream.ctx.vertex_capacity
    if max_degree is None and n * n >= (1 << 31):
        yield from window_triangle_counts_device(
            stream, window_ms, capacity, window_capacity, method
        )
        return

    if max_degree is not None:
        # Overflow checks are deferred by one group (and finalized after
        # the loop): pulling the overflow scalar immediately would sync
        # the host per group and forfeit the pipelining this path exists
        # for (same pattern as the sparse exact stream).
        pending = None  # (overs device array, k)

        def check(p):
            if p is None:
                return
            overs, k = p
            overs = np.asarray(overs)
            if overs[:k].any():
                raise ValueError(
                    f"window adjacency rows overflowed max_degree="
                    f"{max_degree} ({int(overs[:k].sum())} entries "
                    "dropped); raise max_degree"
                )

        def flush(group):
            k = len(group)
            wins = [w for w, _ in group]
            cols = [c for _, c in group]
            if k < batch:
                empty = tuple(np.zeros_like(a) for a in cols[0])
                cols += [empty] * (batch - k)
            kk, nn, vv = (np.stack(x) for x in zip(*cols))
            counts, overs = _window_triangle_count_sparse_group(
                kk, nn, vv, n, max_degree
            )
            if yield_overflow:
                out = [
                    (wins[i], counts[i], overs[i]) for i in range(k)
                ]
            else:
                out = list(zip(wins, [counts[i] for i in range(k)]))
            return out, (overs, k)

        for group in _in_groups(
            _out_windows(stream, window_ms, window_capacity, n), batch
        ):
            out, overs = flush(group)
            check(pending)
            pending = overs
            yield from out
        check(pending)
        return

    pick = _pick_method(method, n)

    def stage(group):
        # Host assembly + H2D on the prefetch thread, overlapping the
        # device counts of earlier groups (the engine's stage_unit
        # pattern). Columns are deduped/compact; pad the group to a shared
        # power-of-two bucket so the compiled kernel sees O(log) shapes.
        k = len(group)
        wins = [w for w, _ in group]
        cols = [c for _, c in group]
        longest = max(c.shape[0] for c in cols)
        bucket = max(1024, 1 << max(0, longest - 1).bit_length())
        # k rows, not batch: a padded row would still compute a full
        # adjacency + count on device. Only the final partial group
        # compiles a second (smaller) K.
        stacked = np.full((k, bucket), segments.INT_MAX, np.int32)
        for i, c in enumerate(cols):
            stacked[i, : c.shape[0]] = c
        return wins, k, jax.device_put(stacked)

    from ..utils.prefetch import prefetch_map

    for wins, k, stacked in prefetch_map(
        stage,
        _in_groups(
            _packed_out_windows(stream, window_ms, window_capacity, n),
            batch,
        ),
        depth=2, workers=1,
    ):
        counts = _window_triangle_count_packed_group(
            stacked, n, n, pick(2 * stacked.shape[1])
        )
        yield from zip(wins, (counts[i] for i in range(k)))


def window_triangles(stream, window_ms: int, capacity: int | None = None,
                     window_capacity: int | None = None,
                     method: str = "auto",
                     max_degree: int | None = None) -> Iterator[tuple]:
    """Per-window triangle counts: yields (window_index, count).

    The reference emits (count, window.maxTimestamp) per window
    (WindowTriangles.java:61-65); window_index * window_ms + window_ms - 1
    recovers that timestamp.

    ``method``: "gather" (VPU, sparse windows), "mxu" (Pallas matmul, dense
    windows; needs capacity % 128 == 0), or "auto" (mxu on TPU when the
    window buffer is dense relative to capacity). ``max_degree`` selects
    the capped-degree sparse kernel — required for large vertex
    capacities (see :func:`window_triangle_counts_batched`).
    """
    if max_degree is not None:
        for w, c in window_triangle_counts_batched(
            stream, window_ms, capacity, window_capacity, method,
            batch=1, max_degree=max_degree,
        ):
            yield w, int(c)
        return
    for w, c in window_triangle_counts_device(
        stream, window_ms, capacity, window_capacity, method
    ):
        yield w, int(c)


def sharded_window_triangles(stream, window_ms: int,
                             capacity: int | None = None,
                             window_capacity: int | None = None,
                             mesh=None,
                             bucket_slack: float = 2.0) -> Iterator[tuple]:
    """Mesh-parallel window triangle count — ``WindowTriangles.java:61-139``
    at parallelism > 1. Yields (window_index, device count scalar).

    The reference runs candidate generation at stream parallelism (each
    subtask emits wedge candidates for its keyed group vertices) and
    matches them against real edges via a second keyed shuffle. Here the
    direction-ALL keyed exchange (:class:`ShardedSnapshotStream`)
    co-locates each group vertex's window neighborhood on its owner
    device; each device then matches its owned canonical edges against
    the window's wedge matrix, and a ``psum`` yields the global count —
    per-device matching work is O(N * E/S). The O(N^2) wedge matrix is
    assembled once per window by an ICI all-reduce of per-device partial
    adjacencies (the mesh analog of the candidate shuffle; for capacities
    past the dense kernel's ~46k limit use the single-device capped-degree
    sparse kernel).

    Exact count parity with :func:`window_triangles` (same canonical-edge
    /wedge-center semantics; asserted by tests on the 8-device CPU mesh).
    """
    from jax.sharding import PartitionSpec as P

    from ..parallel import mesh as mesh_lib
    from ..parallel.mesh import SHARD_AXIS
    from ..parallel.sharded_window import ShardedSnapshotStream

    n = capacity if capacity is not None else stream.ctx.vertex_capacity
    m = mesh if mesh is not None else mesh_lib.make_mesh()
    snap = ShardedSnapshotStream(
        stream, window_ms, "all", window_capacity, m, bucket_slack
    )

    @jax.jit
    def close(view):
        def body(v):
            v = jax.tree.map(lambda x: x[0], v)
            key = jnp.where(v.valid, v.key, 0)
            nbr = jnp.where(v.valid, v.nbr, 0)
            # uint8 partials: the psum'd scratch is n^2 bytes per device,
            # matching the single-device kernel's bool adjacency footprint.
            part = jnp.zeros((n, n), jnp.uint8).at[key, nbr].max(
                v.valid.astype(jnp.uint8), mode="drop"
            )
            adj = jax.lax.psum(part, SHARD_AXIS) > 0
            # Per-device matching over owned canonical edges: with
            # direction ALL, (a, b) a < b lands only on a's owner, so the
            # helper's per-device first-occurrence dedup is globally
            # correct.
            local = _wedge_count_from_adj(adj, v.key, v.nbr, v.valid, n)
            return jax.lax.psum(local, SHARD_AXIS)[None]

        out = mesh_lib.shard_map_fn(
            m, body, in_specs=(P(SHARD_AXIS),), out_specs=P(SHARD_AXIS),
        )(view)
        return out[0]

    for w, view in snap.views():
        yield w, close(view)


# --------------------------------------------------------------------- #
# exact streaming


class TriangleCounts(NamedTuple):
    adj: jax.Array  # i32[N, N] arrival index of each edge (INT_MAX absent)
    counts: jax.Array  # i64[N] per-vertex triangle counters
    total: jax.Array  # i64[] global triangle count
    n_seen: jax.Array  # i32[] edges consumed (arrival-index base)


def fresh_triangle_counts(capacity: int) -> TriangleCounts:
    return TriangleCounts(
        adj=jnp.full((capacity, capacity), segments.INT_MAX, jnp.int32),
        counts=jnp.zeros((capacity,), jnp.int64),
        total=jnp.zeros((), jnp.int64),
        n_seen=jnp.zeros((), jnp.int32),
    )


@jax.jit
def _exact_step_scan(state: TriangleCounts, chunk) -> TriangleCounts:
    """Sequential per-edge intersection within the chunk — the literal
    shape of IntersectNeighborhoods (ExactTriangleCount.java:74-116): a
    triangle increments when its closing edge arrives. Reference
    implementation for parity tests; ~two orders of magnitude slower on
    device than the vectorized step (one gather per edge)."""

    def step(carry, inp):
        adj, counts, total, n_seen = carry
        u, v, ok = inp
        present = adj[u, v] != segments.INT_MAX
        fresh = ok & (u != v) & ~present  # duplicate edges are no-ops
        common = (adj[u] != segments.INT_MAX) & (adj[v] != segments.INT_MAX)
        common = jnp.where(fresh, common, jnp.zeros_like(common))
        c = jnp.sum(common.astype(jnp.int64))
        counts = counts + common.astype(jnp.int64)
        counts = counts.at[u].add(jnp.where(fresh, c, 0))
        counts = counts.at[v].add(jnp.where(fresh, c, 0))
        total = total + c
        idx = jnp.where(fresh, n_seen, segments.INT_MAX)
        adj = adj.at[u, v].min(idx)
        adj = adj.at[v, u].min(idx)
        return (adj, counts, total, n_seen + ok.astype(jnp.int32)), None

    (adj, counts, total, n_seen), _ = jax.lax.scan(
        step, tuple(state), (chunk.src, chunk.dst, chunk.valid)
    )
    return TriangleCounts(adj, counts, total, n_seen)


_EXACT_SLAB = 2048  # edges intersected per vectorized sub-step


@jax.jit
def _exact_step(state: TriangleCounts, chunk) -> TriangleCounts:
    """Vectorized chunk step with exact per-edge closing semantics.

    The adjacency stores each edge's global *arrival index* instead of a
    bit; a triangle is attributed to edge e iff both wedge edges have
    smaller indices — i.e. exactly when its closing edge arrives, the
    reference's IntersectNeighborhoods bookkeeping
    (ExactTriangleCount.java:74-116) — but whole slabs of edges intersect
    at once as masked [slab, N] row ops instead of one scan iteration per
    edge. All accumulation is integer (no float roundoff at any capacity).

    Measured on a 100k-edge / 1k-vertex stream on the TPU chip: ~286M
    edges/s vs ~58k edges/s for the literal per-edge scan
    (:func:`_exact_step_scan`, kept as the parity oracle) — the scan pays
    one dispatch-latency-bound step per edge; the slab path is one fused
    program per chunk.
    """
    n = state.adj.shape[0]
    cap = chunk.capacity
    slab = min(_EXACT_SLAB, cap)
    pad = (-cap) % slab
    src = jnp.pad(chunk.src, (0, pad))
    dst = jnp.pad(chunk.dst, (0, pad))
    ok0 = jnp.pad(chunk.valid, (0, pad)) & (src != dst)
    # Global arrival index of every chunk position (valid edges count).
    arrivals = state.n_seen + jnp.cumsum(
        jnp.pad(chunk.valid, (0, pad)).astype(jnp.int32)
    ) - 1
    idx = jnp.where(ok0, arrivals, segments.INT_MAX)
    # Insert the whole chunk first: scatter-min keeps first arrivals, so
    # in-chunk wedges/duplicates resolve by global order.
    adj = state.adj.at[src, dst].min(idx, mode="drop")
    adj = adj.at[dst, src].min(idx, mode="drop")

    def slab_step(carry, inp):
        counts, total = carry
        su, sv, sidx = inp
        rows_u = adj[su]  # [slab, N] arrival indices of u's neighbors
        rows_v = adj[sv]
        fresh = (sidx != segments.INT_MAX) & (adj[su, sv] == sidx)
        lim = sidx[:, None]
        common = (rows_u < lim) & (rows_v < lim) & fresh[:, None]
        c_e = jnp.sum(common, axis=1).astype(jnp.int64)
        counts = counts + jnp.sum(common, axis=0).astype(jnp.int64)
        counts = counts.at[su].add(jnp.where(fresh, c_e, 0), mode="drop")
        counts = counts.at[sv].add(jnp.where(fresh, c_e, 0), mode="drop")
        return (counts, total + jnp.sum(c_e)), None

    (counts, total), _ = jax.lax.scan(
        slab_step, (state.counts, state.total),
        (src.reshape(-1, slab), dst.reshape(-1, slab),
         idx.reshape(-1, slab)),
    )
    return TriangleCounts(
        adj, counts, total, state.n_seen + chunk.num_valid().astype(jnp.int32)
    )


class ExactTriangleStream:
    """Insertion-only exact triangle counts, chunk-grained emission.

    Iterating yields :class:`TriangleCounts` after each chunk; ``final()``
    drains and returns the last. ``final_counts`` renders the reference's
    observable {vertex: count, -1: global} map (SumAndEmitCounters,
    ExactTriangleCount.java:121-134)."""

    def __init__(self, stream, capacity: int | None = None,
                 arrival_budget: int = int(segments.INT_MAX)):
        self.stream = stream
        self.capacity = (
            int(capacity) if capacity is not None
            else stream.ctx.vertex_capacity
        )
        self.arrival_budget = int(arrival_budget)
        self.stats = {"rebases": 0}

    def __iter__(self) -> Iterator[TriangleCounts]:
        n = self.capacity
        state = fresh_triangle_counts(n)
        seen_host = 0
        for c in self.stream:
            _check_slot_range(
                n, self.stream.ctx.vertex_capacity,
                (c.src, c.valid), (c.dst, c.valid),
            )
            if _needs_rebase(seen_host, c, self.arrival_budget):
                state = _rebase_dense(state)
                seen_host = 0
                self.stats["rebases"] += 1
            seen_host += int(np.asarray(c.valid).sum())
            state = _exact_step(state, c)
            yield state

    def final(self) -> TriangleCounts:
        if not getattr(self, "_drained", False):
            state = None
            for state in self:
                pass
            if state is None:  # empty stream: allocate the zero state lazily
                state = fresh_triangle_counts(self.capacity)
            self._final = state
            self._drained = True
        return self._final

    def final_counts(self) -> dict[int, int]:
        state = self.final()
        ctx = self.stream.ctx
        out = {-1: int(state.total)}
        counts = np.asarray(state.counts)
        nz = np.nonzero(counts)[0]
        for slot, raw in zip(nz.tolist(), ctx.decode(nz).tolist()):
            out[raw] = int(counts[slot])
        return out


def exact_triangle_count(stream, capacity: int | None = None,
                         max_degree: int | None = None,
                         arrival_budget: int = int(segments.INT_MAX)):
    """Exact streaming triangle counts.

    ``max_degree=None`` → dense arrival-index matrix (O(N^2) memory, the
    small-N fast path); ``max_degree=D`` → capped-degree sparse table
    (O(N*D) memory, the N >= 1M path; degree overflow raises).

    Arrival indices are i32; when the stream approaches ``arrival_budget``
    edges (default ~2^31) the summary is REBASED in place — a lossless
    reset of stored indices (see :func:`_needs_rebase`) — so unbounded
    streams never stop or lose counts. ``stats["rebases"]`` counts them.

    Overflow contract (sparse path): overflow checks are deferred by one
    chunk to preserve dispatch pipelining, so the iterator may yield ONE
    state whose counts are corrupt before raising ``ValueError``. Consumers
    acting per yield should gate on the yielded ``state.overflow`` scalar
    (0 = clean); ``final()``/``final_counts()`` never observe a corrupt
    state (the raise fires first)."""
    if max_degree is not None:
        return SparseExactTriangleStream(
            stream, max_degree, capacity, arrival_budget=arrival_budget
        )
    return ExactTriangleStream(stream, capacity,
                               arrival_budget=arrival_budget)


# --------------------------------------------------------------------- #
# sparse (capped-degree) exact streaming — the N >= 1M path


class SparseTriangleCounts(NamedTuple):
    """Capped-degree adjacency: memory O(N * D) instead of O(N^2).

    The reference's ``TreeSet`` neighborhoods handle arbitrary N
    (AdjacencyListGraph.java:31, ExactTriangleCount's buildNeighborhood);
    the dense arrival-index matrix above is the small-N fast path. Here
    each vertex keeps up to ``D`` (neighbor, arrival-index) pairs; degree
    overflow is counted and raised — never a silent wrong count (the
    Twitter-skew discipline: detect the hot vertex, tell the caller to
    raise ``max_degree`` or use the dense path).
    """

    nbr: jax.Array  # i32[N, D] neighbor slots (-1 empty)
    aidx: jax.Array  # i32[N, D] arrival index of that edge
    deg: jax.Array  # i32[N] stored neighbors per vertex
    counts: jax.Array  # i64[N]
    total: jax.Array  # i64[]
    n_seen: jax.Array  # i32[]
    overflow: jax.Array  # i32[] neighbor inserts dropped by the degree cap


def fresh_sparse_triangle_counts(capacity: int,
                                 max_degree: int) -> SparseTriangleCounts:
    return SparseTriangleCounts(
        nbr=jnp.full((capacity, max_degree), -1, jnp.int32),
        aidx=jnp.full((capacity, max_degree), segments.INT_MAX, jnp.int32),
        deg=jnp.zeros((capacity,), jnp.int32),
        counts=jnp.zeros((capacity,), jnp.int64),
        total=jnp.zeros((), jnp.int64),
        n_seen=jnp.zeros((), jnp.int32),
        overflow=jnp.zeros((), jnp.int32),
    )


def _row_append(nbr, aidx, deg, overflow, key, val, idx, ok, max_degree):
    """Append (val, idx) into key's row at its next free slot; conflicting
    appends within the batch get consecutive slots via in-group ranks."""
    n = nbr.shape[0]
    sort_key = jnp.where(ok, key, segments.INT_MAX)
    order = jnp.argsort(sort_key, stable=True)
    k_s = sort_key[order]
    first = jnp.searchsorted(k_s, k_s, side="left")
    rank = jnp.arange(k_s.shape[0], dtype=jnp.int32) - first.astype(jnp.int32)
    slot = deg[jnp.clip(k_s, 0, n - 1)] + rank
    ok_s = ok[order]
    fits = ok_s & (slot < max_degree)
    overflow = overflow + jnp.sum(ok_s & (slot >= max_degree)).astype(jnp.int32)
    flat_len = n * max_degree
    flat = jnp.where(fits, k_s * max_degree + slot, flat_len)
    nbr = nbr.reshape(-1).at[flat].set(val[order], mode="drop").reshape(
        n, max_degree
    )
    aidx = aidx.reshape(-1).at[flat].set(idx[order], mode="drop").reshape(
        n, max_degree
    )
    # Count only inserts that actually landed (mirrors ops/rowtable.row_insert):
    # deg must equal the row fill so any deg-based row slicing stays valid;
    # dropped inserts are recorded solely in ``overflow``.
    deg = segments.masked_scatter_add(deg, k_s, jnp.ones_like(k_s), fits)
    return nbr, aidx, deg, overflow


@partial(jax.jit, static_argnames=("max_degree", "slab"))
def _sparse_exact_step(state: SparseTriangleCounts, chunk,
                       max_degree: int, slab: int) -> SparseTriangleCounts:
    """Chunk step over the capped-degree table: dedup, append both
    directions, then slab-intersect rows with the same arrival-index
    closing-edge attribution as the dense step."""
    D = max_degree
    cap = chunk.capacity
    pad = (-cap) % slab
    src = jnp.pad(chunk.src, (0, pad))
    dst = jnp.pad(chunk.dst, (0, pad))
    ok0 = jnp.pad(chunk.valid, (0, pad)) & (src != dst)
    arrivals = state.n_seen + jnp.cumsum(
        jnp.pad(chunk.valid, (0, pad)).astype(jnp.int32)
    ) - 1
    # Dedup: already-present pairs (row scan) and repeat canonical pairs
    # within the chunk are no-ops (ExactTriangleCount counts each edge
    # once; the dense path gets this from scatter-min).
    present = jnp.any(state.nbr[src] == dst[:, None], axis=1)
    a = jnp.minimum(src, dst)
    b = jnp.maximum(src, dst)
    first_in_chunk = segments.unique_pairs_mask(a, b, ok0, state.deg.shape[0])
    fresh = ok0 & ~present & first_in_chunk
    idx = jnp.where(fresh, arrivals, segments.INT_MAX)

    nbr, aidx, deg, overflow = _row_append(
        state.nbr, state.aidx, state.deg, state.overflow,
        src, dst, idx, fresh, D,
    )
    nbr, aidx, deg, overflow = _row_append(
        nbr, aidx, deg, overflow, dst, src, idx, fresh, D,
    )

    def slab_step(carry, inp):
        counts, total = carry
        su, sv, sidx, sfresh = inp
        nu = nbr[su]  # [slab, D]
        au = aidx[su]
        nv = nbr[sv]
        av = aidx[sv]
        lim = sidx[:, None]
        ok_u = (nu >= 0) & (au < lim)
        ok_v = (nv >= 0) & (av < lim)
        # [slab, D, D] equality: w in both rows with earlier arrivals.
        match = (
            (nu[:, :, None] == nv[:, None, :])
            & ok_u[:, :, None] & ok_v[:, None, :]
            & sfresh[:, None, None]
        )
        c_e = jnp.sum(match, axis=(1, 2)).astype(jnp.int64)
        # Common-vertex contributions: +1 to each matched w. Empty slots
        # hold -1, which would WRAP as a scatter index — route them (and
        # every non-matching entry) past the array so mode="drop" skips.
        w_hits = jnp.sum(match, axis=2)  # [slab, D] per u-row entry
        n_counts = counts.shape[0]
        w_idx = jnp.where(ok_u & (w_hits > 0), nu, n_counts)
        counts = counts.at[w_idx.reshape(-1)].add(
            w_hits.reshape(-1).astype(jnp.int64), mode="drop"
        )
        counts = counts.at[su].add(jnp.where(sfresh, c_e, 0), mode="drop")
        counts = counts.at[sv].add(jnp.where(sfresh, c_e, 0), mode="drop")
        return (counts, total + jnp.sum(c_e)), None

    (counts, total), _ = jax.lax.scan(
        slab_step, (state.counts, state.total),
        (src.reshape(-1, slab), dst.reshape(-1, slab),
         idx.reshape(-1, slab), fresh.reshape(-1, slab)),
    )
    return SparseTriangleCounts(
        nbr, aidx, deg, counts, total,
        state.n_seen + chunk.num_valid().astype(jnp.int32), overflow,
    )


class SparseExactTriangleStream:
    """Exact triangle counts over a capped-degree sparse adjacency —
    same observable surface as :class:`ExactTriangleStream`, memory
    O(N * max_degree)."""

    def __init__(self, stream, max_degree: int, capacity: int | None = None,
                 slab: int | None = None,
                 arrival_budget: int = int(segments.INT_MAX)):
        self.stream = stream
        self.max_degree = int(max_degree)
        self.capacity = (
            int(capacity) if capacity is not None
            else stream.ctx.vertex_capacity
        )
        # Keep [slab, D, D] intersection tensors around ~2^22 elements.
        self.slab = (
            int(slab) if slab is not None
            else max(8, (1 << 22) // (self.max_degree ** 2))
        )
        self.arrival_budget = int(arrival_budget)
        self.stats = {"rebases": 0}

    def _overflow_error(self, n: int) -> ValueError:
        return ValueError(
            f"{n} neighbor inserts exceeded max_degree {self.max_degree} "
            f"(degree-skewed stream); raise max_degree or use the dense path"
        )

    def __iter__(self) -> Iterator[SparseTriangleCounts]:
        state = fresh_sparse_triangle_counts(self.capacity, self.max_degree)
        prev_overflow = None
        seen_host = 0
        for c in self.stream:
            _check_slot_range(
                self.capacity, self.stream.ctx.vertex_capacity,
                (c.src, c.valid), (c.dst, c.valid),
            )
            if _needs_rebase(seen_host, c, self.arrival_budget):
                state = _rebase_sparse(state)
                seen_host = 0
                self.stats["rebases"] += 1
            seen_host += int(np.asarray(c.valid).sum())
            state = _sparse_exact_step(state, c, self.max_degree, self.slab)
            # Check the PREVIOUS chunk's overflow after dispatching the
            # current one: the host sync lands on an already-finished
            # computation, preserving async overlap. (At most one corrupt
            # state is yielded before the raise; final() never sees it.)
            if prev_overflow is not None and int(prev_overflow):
                raise self._overflow_error(int(prev_overflow))
            prev_overflow = state.overflow
            yield state
        if prev_overflow is not None and int(prev_overflow):
            raise self._overflow_error(int(prev_overflow))

    def final(self) -> SparseTriangleCounts:
        if not getattr(self, "_drained", False):
            state = None
            for state in self:
                pass
            if state is None:
                state = fresh_sparse_triangle_counts(
                    self.capacity, self.max_degree
                )
            self._final = state
            self._drained = True
        return self._final

    def final_counts(self) -> dict[int, int]:
        state = self.final()
        ctx = self.stream.ctx
        out = {-1: int(state.total)}
        counts = np.asarray(state.counts)
        nz = np.nonzero(counts)[0]
        for slot, raw in zip(nz.tolist(), ctx.decode(nz).tolist()):
            out[raw] = int(counts[slot])
        return out


# --------------------------------------------------------------------- #
# sampled estimation


class SamplerState(NamedTuple):
    src: jax.Array  # i32[S] sampled edge endpoints
    trg: jax.Array
    third: jax.Array  # i32[S] sampled third vertex
    src_found: jax.Array  # bool[S]
    trg_found: jax.Array  # bool[S]
    v_at: jax.Array  # i32[S] live vertex count when this sample was drawn
    edge_count: jax.Array  # i32[] edges seen
    keys: jax.Array  # u32[S, 2] per-instance PRNG keys


def _fresh_sampler(num_samples: int, seed: int) -> SamplerState:
    s = num_samples
    return SamplerState(
        src=jnp.full((s,), -1, jnp.int32),
        trg=jnp.full((s,), -1, jnp.int32),
        third=jnp.full((s,), -1, jnp.int32),
        src_found=jnp.zeros((s,), bool),
        trg_found=jnp.zeros((s,), bool),
        v_at=jnp.zeros((s,), jnp.int32),
        edge_count=jnp.zeros((), jnp.int32),
        # Per-instance keys: instance j's randomness depends only on its own
        # key stream, so estimates are identical however the instance axis
        # is laid out across devices (the broadcast/incidence duality).
        keys=jax.random.split(jax.random.PRNGKey(seed), s),
    )


@jax.jit
def _sampler_step(state: SamplerState, chunk,
                  num_vertices: jax.Array) -> SamplerState:
    """Advance all S reservoir instances over the chunk's edges in stream
    order (TriangleSampler.flatMap, BroadcastTriangleCount.java:79-126).

    ``num_vertices`` is traced (the live vertex count grows with the
    stream); the third-vertex draw excludes both endpoints. Self-loop edges
    are skipped entirely — they can close no wedge, and sampling one would
    skew the third-vertex distribution (the reference's rejection loop
    never admits them).
    """

    def step(st, inp):
        u, v, ok = inp
        ok = ok & (u != v)  # self-loops: no-op events
        i = st.edge_count + 1  # 1-based edge index
        splits = jax.vmap(lambda k: jax.random.split(k, 3))(st.keys)
        keys, k1, k2 = splits[:, 0], splits[:, 1], splits[:, 2]
        # Coin.flip: resample this instance's edge with probability 1/i.
        coin = (
            jax.vmap(jax.random.uniform)(k1) * i.astype(jnp.float32) < 1.0
        ) & ok
        # Third vertex uniform over V \ {u, v}: draw from [0, V-2) and
        # shift past both excluded endpoints in ascending order.
        a = jnp.minimum(u, v)
        b = jnp.maximum(u, v)
        cand = jax.vmap(
            lambda k: jax.random.randint(
                k, (), 0, jnp.maximum(num_vertices - 2, 1), jnp.int32
            )
        )(k2)
        cand = cand + (cand >= a).astype(jnp.int32)
        cand = cand + (cand >= b).astype(jnp.int32)
        src = jnp.where(coin, u, st.src)
        trg = jnp.where(coin, v, st.trg)
        third = jnp.where(coin, cand, st.third)
        src_found = jnp.where(coin, False, st.src_found)
        trg_found = jnp.where(coin, False, st.trg_found)
        # The vertex count the third-vertex draw was consistent with: the
        # estimate scales each instance by ITS draw-time V, not the final
        # one (a sample drawn at V=10 hit with probability ~1/8; scaling it
        # by a later V would bias the estimator on growing streams).
        v_at = jnp.where(coin, num_vertices, st.v_at)
        # Match the two remaining wedge edges against this edge.
        m_src = ((u == src) & (v == third)) | ((u == third) & (v == src))
        m_trg = ((u == trg) & (v == third)) | ((u == third) & (v == trg))
        src_found = src_found | (m_src & ok)
        trg_found = trg_found | (m_trg & ok)
        return SamplerState(
            src, trg, third, src_found, trg_found, v_at,
            st.edge_count + ok.astype(jnp.int32), keys,
        ), None

    out, _ = jax.lax.scan(step, state, (chunk.src, chunk.dst, chunk.valid))
    return out


def sampler_estimate(state: SamplerState, num_vertices=None) -> float:
    """(1/S) * Σ_j beta_j (V_j - 2) * edge_count — TriangleSummer's scaling
    (BroadcastTriangleCount.java:158-166), with each instance scaled by the
    vertex count its third-vertex draw was made against (V_j == V when the
    caller fixes ``num_vertices``, reproducing the reference formula
    exactly). The sum spans the whole (possibly device-sharded) instance
    axis: under jit over a mesh-placed state this lowers to a psum."""
    beta = (state.src_found & state.trg_found).astype(jnp.float32)
    v = (
        state.v_at if num_vertices is None
        else jnp.full_like(state.v_at, num_vertices)
    )
    scaled = jnp.sum(beta * jnp.maximum(v - 2, 0).astype(jnp.float32))
    s = state.src.shape[0]
    return float(scaled / s * state.edge_count.astype(jnp.float32))


def sampled_triangle_count(stream, num_samples: int,
                           num_vertices: int | None = None,
                           seed: int = 0xDEADBEEF,
                           mesh=None) -> Iterator[float]:
    """Streaming estimate, one value per chunk.

    ``seed`` defaults to the incidence example's seeded RNG
    (IncidenceSamplingTriangleCount.java:78) for reproducibility.

    ``num_vertices`` defaults to the stream's *live* vertex count per chunk
    (the reference scales by the true |V|; the slot capacity can be much
    larger, which would blow up variance via phantom third-vertex draws).

    ``mesh`` shards the instance axis over the devices (the
    BroadcastTriangleCount deployment: edges replicated to every device,
    ``BroadcastTriangleCount.java:41-45``; each device owns
    num_samples/S reservoir instances like the incidence fan-out,
    ``IncidenceSamplingTriangleCount.java:87-122``). The per-instance key
    streams make the estimate bitwise-identical to the single-device
    layout; the beta sum reduces over ICI.
    """
    state = _fresh_sampler(num_samples, seed)
    if mesh is not None:
        from ..parallel import mesh as mesh_lib

        if num_samples % mesh_lib.num_shards(mesh):
            raise ValueError(
                f"num_samples {num_samples} not divisible by "
                f"{mesh_lib.num_shards(mesh)} shards"
            )
        ec = mesh_lib.device_put_replicated(mesh, state.edge_count)
        state = state._replace(
            **{
                f: mesh_lib.device_put_sharded_leading(mesh, getattr(state, f))
                for f in SamplerState._fields if f != "edge_count"
            },
            edge_count=ec,
        )
    for c in stream:
        v = (
            num_vertices if num_vertices is not None
            else stream.ctx.table.num_vertices
        )
        state = _sampler_step(state, c, jnp.int32(v))
        yield sampler_estimate(state, num_vertices)
