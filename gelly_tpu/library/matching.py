"""Greedy ½-approximation weighted matching (centralized stage).

TPU-native re-design of ``M/example/CentralizedWeightedMatching.java:36-113``:
the reference is a parallelism-1 stateful flatMap holding a ``Set<Edge>``; a
new edge evicts its colliding matched edges iff its weight exceeds twice
their combined weight. Here the matching lives in two dense arrays —
``partner[i32 N]`` (-1 = unmatched) and ``weight[N]`` (stored at both
endpoints; f64 on the host paths like the reference's Java doubles, f32 on
the device path) — and the inherently sequential per-edge decision folds
chunk by chunk on the host via a native C++ stage (``native/matching.cc``)
or, for pipelines that must stay resident, as a ``lax.scan`` on a single
device (the stage is centralized in the reference too, ``:59-60``).

Numeric divergence bound (pinned by
``test_matching_f32_f64_threshold_divergence``): the two paths decide the
eviction test ``w > 2*(wu + wv)`` in different precisions, so they can
disagree exactly when the challenger's weight lands between the f64 and
f32 roundings of the doubled collision sum — a window of at most one f32
ulp of that sum. The host path is the reference-exact oracle (Java
doubles); the device path trades that last ulp for staying resident.
"""

from __future__ import annotations

from typing import Iterator, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class MatchingState(NamedTuple):
    partner: jax.Array  # i32[N], -1 unmatched
    weight: jax.Array  # f32[N] weight of the matched edge at this endpoint


class MatchingEvent(NamedTuple):
    """ADD/REMOVE event — the reference's observable output
    (M/util/MatchingEvent.java:24-42)."""

    type: str  # "ADD" | "REMOVE"
    src: int  # raw vertex ids
    dst: int
    weight: float


@jax.jit
def _matching_step(state: MatchingState, chunk) -> MatchingState:
    def step(s, inp):
        u, v, w, ok = inp
        partner, weight = s
        pu, pv = partner[u], partner[v]
        # Colliding matched edges: u's and v's current matches. If u and v
        # are matched to each other that is one edge, not two.
        wu = jnp.where(pu >= 0, weight[u], 0.0)
        wv = jnp.where(pv >= 0, weight[v], 0.0)
        same_edge = (pu == v) & (pv == u) & (pu >= 0)
        coll_sum = jnp.where(same_edge, wu, wu + wv)
        take = ok & (u != v) & (w > 2.0 * coll_sum)
        # Evict collisions: clear u's and v's partners (and their partners).
        def clear(partner, weight, x, px):
            do = take & (px >= 0)
            partner = partner.at[px].set(
                jnp.where(do, -1, partner[px]))
            weight = weight.at[px].set(jnp.where(do, 0.0, weight[px]))
            partner = partner.at[x].set(jnp.where(do, -1, partner[x]))
            weight = weight.at[x].set(jnp.where(do, 0.0, weight[x]))
            return partner, weight

        partner, weight = clear(partner, weight, u, pu)
        partner, weight = clear(partner, weight, v, pv)
        # Add (u, v, w).
        partner = partner.at[u].set(jnp.where(take, v, partner[u]))
        partner = partner.at[v].set(jnp.where(take, u, partner[v]))
        weight = weight.at[u].set(jnp.where(take, w, weight[u]))
        weight = weight.at[v].set(jnp.where(take, w, weight[v]))
        return MatchingState(partner, weight), None

    out, _ = jax.lax.scan(
        step, state,
        (chunk.src, chunk.dst, chunk.val.astype(jnp.float32), chunk.valid),
    )
    return out


_NATIVE = None  # test hook: False forces the Python fallback


def _native_ok() -> bool:
    if _NATIVE is not None:
        return _NATIVE
    from ..utils import native

    return native.available("matching")


def _matching_step_host(state: MatchingState, chunk,
                        events: list | None = None) -> MatchingState:
    """Host per-edge fold over the chunk's valid edges — the default path.

    The stage is a strictly-sequential scalar state machine (the reference
    runs it as one parallelism-1 operator, CentralizedWeightedMatching.java
    :59-60); a device lax.scan pays per-step scatter latency for ~10 scalar
    ops of real work, so the host path is ~100x faster. It runs as a native
    C++ fold (``native/matching.cc``) when the toolchain is available, with
    this Python loop as the fallback. The device variant remains available
    (device=True) for pipelines that must stay resident.
    """
    partner = np.asarray(state.partner).copy()
    weight = np.asarray(state.weight).copy()
    if _native_ok():
        from ..utils.native import matching_chunk_fold

        out = matching_chunk_fold(
            np.asarray(chunk.src), np.asarray(chunk.dst),
            np.asarray(chunk.val), np.asarray(chunk.valid),
            partner.shape[0], partner, weight,
            want_events=events is not None,
        )
        if events is not None:
            types, a, b, w = out
            for t, x, y, wt in zip(
                types.tolist(), a.tolist(), b.tolist(), w.tolist()
            ):
                events.append(MatchingEvent(
                    "ADD" if t == 0 else "REMOVE", x, y, wt
                ))
        return MatchingState(partner, weight)
    m = np.asarray(chunk.valid)
    for u, v, w in zip(
        np.asarray(chunk.src)[m].tolist(),
        np.asarray(chunk.dst)[m].tolist(),
        np.asarray(chunk.val)[m].tolist(),
    ):
        if u == v:
            continue
        pu, pv = int(partner[u]), int(partner[v])
        same = pu == v and pv == u  # colliding edge is (u, v) itself
        if same:
            coll_sum = weight[u]
        else:
            coll_sum = (weight[u] if pu >= 0 else 0.0) + (
                weight[v] if pv >= 0 else 0.0
            )
        if w > 2.0 * coll_sum:
            evict = ((u, pu),) if same else ((u, pu), (v, pv))
            for x, px in evict:
                if px >= 0:
                    if events is not None:
                        events.append(MatchingEvent(
                            "REMOVE", x, px, float(weight[x])
                        ))
                    partner[px] = -1
                    weight[px] = 0.0
                    partner[x] = -1
                    weight[x] = 0.0
            partner[u], partner[v] = v, u
            weight[u] = weight[v] = w
            if events is not None:
                events.append(MatchingEvent("ADD", u, v, float(w)))
    return MatchingState(partner, weight)


class WeightedMatchingStream:
    """Iterate for per-chunk states; ``final_matching`` returns the matched
    raw-id edge set and ``total_weight`` its weight."""

    def __init__(self, stream, device: bool = False):
        self.stream = stream
        self.device = device

    def __iter__(self) -> Iterator[MatchingState]:
        n = self.stream.ctx.vertex_capacity
        if self.device:
            state = MatchingState(
                partner=jnp.full((n,), -1, jnp.int32),
                weight=jnp.zeros((n,), jnp.float32),
            )
            for c in self.stream:
                state = _matching_step(state, c)
                yield state
            return
        state = MatchingState(
            partner=np.full((n,), -1, np.int32),
            weight=np.zeros((n,), np.float64),
        )
        for c in self.stream:
            state = _matching_step_host(state, c)
            yield state

    def events(self) -> Iterator[MatchingEvent]:
        """ADD/REMOVE event stream with raw vertex ids — the reference's
        collector output (WeightedMatchingFlatMapper, ADD at :103-104,
        REMOVE at :99-101). Host path only: a device=True stream must use
        final()/final_matching() (mixing the two would make results depend
        on call order across the f32 device and f64 host thresholds)."""
        if self.device:
            raise NotImplementedError(
                "events() is host-path only; use device=False"
            )
        ctx = self.stream.ctx
        n = ctx.vertex_capacity
        state = MatchingState(
            partner=np.full((n,), -1, np.int32),
            weight=np.zeros((n,), np.float64),
        )
        for c in self.stream:
            evs: list = []
            state = _matching_step_host(state, c, evs)
            if evs:
                # One batched decode per chunk (VertexTable probe + array
                # construction are per-call host costs).
                flat = np.array([x for e in evs for x in (e.src, e.dst)])
                raw = ctx.decode(flat).tolist()
                for i, e in enumerate(evs):
                    yield MatchingEvent(
                        e.type, raw[2 * i], raw[2 * i + 1], e.weight
                    )
        # A full drain just happened: cache it so final()/total_weight()
        # don't recompute the whole stream.
        self._final = state
        self._drained = True

    def final(self) -> MatchingState:
        if not getattr(self, "_drained", False):
            state = None
            for state in self:
                pass
            if state is None:  # empty stream
                n = self.stream.ctx.vertex_capacity
                state = MatchingState(
                    partner=np.full((n,), -1, np.int32),
                    weight=np.zeros((n,), np.float64),
                )
            self._final = state
            self._drained = True
        return self._final

    def final_matching(self) -> list[tuple[int, int, float]]:
        state = self.final()
        ctx = self.stream.ctx
        partner = np.asarray(state.partner)
        weight = np.asarray(state.weight)
        out = []
        for u in np.nonzero(partner >= 0)[0].tolist():
            v = int(partner[u])
            if u < v:  # each matched pair once
                ru, rv = ctx.decode(np.array([u, v])).tolist()
                out.append((min(ru, rv), max(ru, rv), float(weight[u])))
        return sorted(out)

    def total_weight(self) -> float:
        return sum(w for _, _, w in self.final_matching())


def weighted_matching(stream, device: bool = False) -> WeightedMatchingStream:
    return WeightedMatchingStream(stream, device=device)
