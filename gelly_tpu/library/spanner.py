"""Streaming k-Spanner.

TPU-native re-design of ``M/library/Spanner.java:40-118`` +
``M/summaries/AdjacencyListGraph.java:29-140``: keep an edge iff its
endpoints are NOT already within k hops in the spanner built so far
(``UpdateLocal.foldEdges``, ``Spanner.java:70-77``); cross-partition combine
re-applies the same gate edge-by-edge while inserting the smaller spanner
into the larger (``CombineSpanners.reduce``, ``:91-116``).

The summary is a dense ``bool[N, N]`` adjacency (the BFS working set) plus a
fixed-capacity edge list (the spanner's materialized output and the
combine's iteration order — the analog of the reference's insertion-ordered
adjacency map). ``boundedBFS`` (``AdjacencyListGraph.java:79-116``) becomes
k rounds of boolean frontier×adjacency expansion; the per-edge decision is
inherently sequential (each acceptance changes later decisions —
SURVEY.md §7 hard-part #2), so the chunk fold is a ``lax.scan`` whose step
does the k-round reachability check, all on device.

Exact edge-set parity with the reference is order-dependent; tests assert
the spanner *properties* instead (subset of input; per-edge stretch ≤ k;
connectivity preserved), the approach the reference's own unit test takes
scenario-wise (``T/util/AdjacencyListGraphTest.java:57-87``).

WHICH PATH TO USE: the order-exact production path is
:class:`HostSpannerStream` (the native C++ bounded-BFS stage, multi-M
edges/s, exact-parity-tested) — like the reference's op, the sequential
gate is a scalar state machine. For k == 2 the device is now a peer
option (round 5): ``gate_batch`` switches the sparse fold to the batched
closed-form distance-2 gate (:func:`_sparse_fold_chunk_k2`, one D x D
row intersection per candidate) — measured **~2M edges/s at n_v = 2^20**
on v5e (vs ~5k for the per-edge BFS scan), with conservative-acceptance
semantics (extra edges possible, stretch bound never broken). For
general k the device aggregates remain semantics/combine plumbing: the
per-edge BFS fold runs ~5k edges/s (dense) and the sparse
CROSS-PARTITION combine batch-gates the donor's edges
(:func:`_sparse_insert_edges_batched`) at cost ∝ accepted edges.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.aggregation import SummaryAggregation


class SpannerSummary(NamedTuple):
    adj: jax.Array  # bool[N, N] spanner adjacency (undirected)
    esrc: jax.Array  # i32[E] accepted edges, insertion order
    edst: jax.Array  # i32[E]
    n: jax.Array  # i32[] number of accepted edges
    overflow: jax.Array  # bool[] edge-list capacity exceeded (sticky)


def _within_k(adj: jax.Array, u: jax.Array, v: jax.Array, k: int) -> jax.Array:
    """dist(u, v) <= k in adj — boundedBFS (AdjacencyListGraph.java:79-116)
    as k rounds of frontier expansion."""
    n = adj.shape[0]
    frontier = jnp.zeros((n,), bool).at[u].set(True)

    def body(_, f):
        return f | jnp.any(adj & f[:, None], axis=0)

    frontier = jax.lax.fori_loop(0, k, body, frontier)
    return frontier[v]


def _insert_edges(summary: SpannerSummary, src, dst, valid, k: int
                  ) -> SpannerSummary:
    """Sequentially gate-and-insert edges (the order-dependent hot loop)."""

    def step(s, inp):
        u, v, ok = inp
        live = ok & (u != v)
        reach = _within_k(s.adj, u, v, k)
        take = live & ~reach
        adj = s.adj.at[u, v].max(take)
        adj = adj.at[v, u].max(take)
        # List append only while there is room; adjacency stays correct
        # either way and decode raises on the sticky overflow flag.
        store = take & (s.n < s.esrc.shape[0])
        slot = jnp.minimum(s.n, s.esrc.shape[0] - 1)
        esrc = s.esrc.at[slot].set(jnp.where(store, u, s.esrc[slot]))
        edst = s.edst.at[slot].set(jnp.where(store, v, s.edst[slot]))
        overflow = s.overflow | (take & ~store)
        return SpannerSummary(
            adj, esrc, edst, s.n + take.astype(jnp.int32), overflow
        ), None

    out, _ = jax.lax.scan(step, summary, (src, dst, valid))
    return out


def _insert_edges_batched(s: SpannerSummary, esrc, edst, n_valid,
                          k: int, batch: int = 64) -> SpannerSummary:
    """Dense analog of :func:`_sparse_insert_edges_batched` — the combine's
    batch gate (same batch size and candidate order, so the dense and
    sparse plans accept identical sets when caps don't bind)."""
    B = batch
    cap = esrc.shape[0]
    # n_valid counts ACCEPTED edges including ones whose store overflowed
    # the lane capacity; clamp so an overflowed donor doesn't spin extra
    # start-clamped iterations re-gating the tail lanes.
    n_valid = jnp.minimum(n_valid, cap)
    pad = (-cap) % B
    esrc_p = jnp.pad(esrc, (0, pad))
    edst_p = jnp.pad(edst, (0, pad))

    def cond(st):
        _, start = st
        return start < n_valid

    def body(st):
        s_, start = st
        u = jax.lax.dynamic_slice(esrc_p, (start,), (B,))
        v = jax.lax.dynamic_slice(edst_p, (start,), (B,))
        ok = (start + jnp.arange(B, dtype=jnp.int32)) < n_valid
        reach = jax.vmap(lambda uu, vv: _within_k(s_.adj, uu, vv, k))(u, v)
        take = ok & (u != v) & ~reach
        adj = s_.adj.at[u, v].max(take)
        adj = adj.at[v, u].max(take)
        pos = s_.n + jnp.cumsum(take.astype(jnp.int32)).astype(jnp.int32) - 1
        store = take & (pos < s_.esrc.shape[0])
        tgt = jnp.where(store, pos, s_.esrc.shape[0])
        esrc2 = s_.esrc.at[tgt].set(u, mode="drop")
        edst2 = s_.edst.at[tgt].set(v, mode="drop")
        overflow = s_.overflow | jnp.any(take & ~store)
        return SpannerSummary(
            adj, esrc2, edst2,
            s_.n + jnp.sum(take).astype(jnp.int32), overflow,
        ), start + B

    out, _ = jax.lax.while_loop(cond, body, (s, jnp.int32(0)))
    return out


# ------------------------------------------------------------------ #
# sparse (capped-degree) spanner — the N >= 1M path


class SparseSpannerSummary(NamedTuple):
    nbr: jax.Array  # i32[N, D] spanner adjacency rows (-1 empty)
    deg: jax.Array  # i32[N]
    esrc: jax.Array  # i32[E] accepted edges, insertion order
    edst: jax.Array  # i32[E]
    n: jax.Array  # i32[] accepted edges
    overflow: jax.Array  # bool[] edge-list capacity exceeded (sticky)
    deg_overflow: jax.Array  # i32[] adjacency inserts dropped by the cap


def _within_k_sparse(nbr, u, v, k: int, frontier_cap: int) -> jax.Array:
    """boundedBFS over capped-degree rows with a bounded frontier set.

    Conservative by construction: a frontier or degree overflow can only
    UNDER-report reachability, which makes the spanner accept an extra
    edge — never reject wrongly — so the k-stretch property survives every
    capacity limit (SURVEY.md §7 hard-part #2's safe degradation).
    """
    n = nbr.shape[0]
    sent = jnp.int32(n)  # sentinel sorts last under unique
    frontier = jnp.full((frontier_cap,), sent, jnp.int32).at[0].set(u)

    def body(_, f):
        live = f < sent
        rows = nbr[jnp.where(live, f, 0)]  # [F, D]
        cand = jnp.where(live[:, None] & (rows >= 0), rows, sent)
        merged = jnp.concatenate([f, cand.reshape(-1)])
        return jnp.unique(merged, size=frontier_cap, fill_value=sent)

    frontier = jax.lax.fori_loop(0, k, body, frontier)
    return jnp.any(frontier == v)


def _sparse_insert_edges(s: SparseSpannerSummary, src, dst, valid, k: int,
                         max_degree: int, frontier_cap: int
                         ) -> SparseSpannerSummary:
    """Sequential gate-and-insert over the capped-degree table."""
    D = max_degree

    from ..ops.rowtable import row_insert

    def step(s, inp):
        u, v, ok = inp
        live = ok & (u != v)
        reach = _within_k_sparse(s.nbr, u, v, k, frontier_cap)
        take = live & ~reach
        # Row appends (u -> v and v -> u) at each row's next free slot;
        # no dedupe needed (a duplicate edge is always within k and never
        # taken).
        nbr, deg, dover = s.nbr, s.deg, s.deg_overflow
        for a, b in ((u, v), (v, u)):
            nbr, deg, dover = row_insert(
                nbr, deg, dover, a, b, take, D, dedupe=False
            )
        store = take & (s.n < s.esrc.shape[0])
        slot = jnp.minimum(s.n, s.esrc.shape[0] - 1)
        esrc = s.esrc.at[slot].set(jnp.where(store, u, s.esrc[slot]))
        edst = s.edst.at[slot].set(jnp.where(store, v, s.edst[slot]))
        overflow = s.overflow | (take & ~store)
        return SparseSpannerSummary(
            nbr, deg, esrc, edst, s.n + take.astype(jnp.int32), overflow,
            dover,
        ), None

    out, _ = jax.lax.scan(step, s, (src, dst, valid))
    return out


def _sparse_fold_chunk_k2(s: SparseSpannerSummary, src, dst, valid,
                          max_degree: int, sub: int
                          ) -> SparseSpannerSummary:
    """Whole-chunk batched gate for k == 2 — the device-rate fold
    (VERDICT r4 item 9: the per-edge ``lax.scan`` gate ran ~5k edges/s;
    this path measures >1M at n_v = 2^20).

    k = 2 admits a CLOSED-FORM gate: ``dist(u, v) <= 2`` iff v is a
    direct neighbor of u or the two capped-degree rows share an entry —
    one D x D row intersection per candidate, fully vectorized (no
    frontier expansion, no per-edge BFS). The chunk folds as ``sub``-lane
    sub-batches: each sub-batch gates against the adjacency INCLUDING
    every earlier sub-batch's acceptances, and accepts all its
    gate-passers at once (exact duplicates within a sub-batch are
    deduped; non-duplicate redundancy within one sub-batch is the same
    conservative degradation class as the frontier/degree caps — extra
    edges, never a broken stretch bound, since every REJECTED edge was
    proven within 2).
    """
    D = max_degree
    B = src.shape[0]
    pad = (-B) % sub
    u = jnp.pad(src, (0, pad))
    v = jnp.pad(dst, (0, pad))
    ok = jnp.pad(valid, (0, pad))
    nb = (B + pad) // sub
    n_cap = s.nbr.shape[0]

    def body(s_, args):
        uu, vv, oo = args
        live = oo & (uu != vv)
        ru = s_.nbr[uu]  # [sub, D]
        rv = s_.nbr[vv]
        direct = jnp.any(ru == vv[:, None], axis=1)
        common = jnp.any(
            (ru[:, :, None] == rv[:, None, :]) & (ru[:, :, None] >= 0),
            axis=(1, 2),
        )
        take = live & ~(direct | common)
        # Exact-duplicate dedup inside the sub-batch (across sub-batches
        # the gate itself rejects duplicates: the first copy is a direct
        # neighbor by then).
        a_ = jnp.minimum(uu, vv)
        b_ = jnp.maximum(uu, vv)
        key = jnp.where(
            take, a_.astype(jnp.int64) * n_cap + b_, jnp.int64(-1)
        )
        skey, sidx = jax.lax.sort(
            (key, jnp.arange(sub, dtype=jnp.int32)), num_keys=1
        )
        first = ((skey != jnp.roll(skey, 1)).at[0].set(True)) & (skey >= 0)
        take = jnp.zeros((sub,), bool).at[sidx].set(first)
        nbr, deg, dover = s_.nbr, s_.deg, s_.deg_overflow
        for a, b in ((uu, vv), (vv, uu)):
            nbr, deg, dover = _row_append_batch(
                nbr, deg, dover, a, b, take, D
            )
        pos = s_.n + jnp.cumsum(take.astype(jnp.int32)) - 1
        store = take & (pos < s_.esrc.shape[0])
        tgt = jnp.where(store, pos, s_.esrc.shape[0])
        esrc = s_.esrc.at[tgt].set(uu, mode="drop")
        edst = s_.edst.at[tgt].set(vv, mode="drop")
        return SparseSpannerSummary(
            nbr, deg, esrc, edst,
            s_.n + jnp.sum(take).astype(jnp.int32),
            s_.overflow | jnp.any(take & ~store), dover,
        ), None

    out, _ = jax.lax.scan(
        body, s,
        (u.reshape(nb, sub), v.reshape(nb, sub), ok.reshape(nb, sub)),
    )
    return out


def _row_append_batch(nbr, deg, over, key, val, ok, max_degree: int):
    """Batched row append with in-batch rank handling (conflicting appends
    to one row get consecutive slots — the batch analog of row_insert)."""
    n = nbr.shape[0]
    sort_key = jnp.where(ok, key, jnp.int32(n))
    order = jnp.argsort(sort_key, stable=True)
    k_s = sort_key[order]
    first = jnp.searchsorted(k_s, k_s, side="left")
    rank = jnp.arange(k_s.shape[0], dtype=jnp.int32) - first.astype(jnp.int32)
    slot = deg[jnp.clip(k_s, 0, n - 1)] + rank
    ok_s = ok[order]
    fits = ok_s & (slot < max_degree)
    over = over + jnp.sum(ok_s & (slot >= max_degree)).astype(jnp.int32)
    flat = jnp.where(fits, k_s * max_degree + slot, n * max_degree)
    nbr = nbr.reshape(-1).at[flat].set(
        val[order], mode="drop"
    ).reshape(n, max_degree)
    deg = deg.at[jnp.where(fits, k_s, n)].add(1, mode="drop")
    return nbr, deg, over


def _sparse_insert_edges_batched(s: SparseSpannerSummary, esrc, edst,
                                 n_valid, k: int, max_degree: int,
                                 frontier_cap: int,
                                 batch: int = 64) -> SparseSpannerSummary:
    """Batch-gated combine insert (VERDICT r3 item 10): gate ``batch``
    candidates at once against the CURRENT adjacency (vmapped bounded
    BFS), accept every candidate the gate clears, insert, advance — a
    ``while_loop`` over batches that stops at ``n_valid``, so combine cost
    is ∝ the donor spanner's ACTUAL accepted edges, not the max_edges lane
    capacity the old per-lane scan always paid.

    Order note: candidates within one batch are not re-gated against each
    other's acceptances, so the accept set can carry a few extra edges a
    strictly sequential gate would have rejected — the same conservative
    degradation class as the frontier/degree caps (extra edges, never a
    broken k-stretch bound: every REJECTED edge was verified within k).
    """
    D = max_degree
    B = batch
    cap = esrc.shape[0]
    # Same clamp as _insert_edges_batched: n counts accepted edges even
    # when the store overflowed the lane capacity.
    n_valid = jnp.minimum(n_valid, cap)
    pad = (-cap) % B
    esrc_p = jnp.pad(esrc, (0, pad))
    edst_p = jnp.pad(edst, (0, pad))

    def cond(st):
        _, start = st
        return start < n_valid

    def body(st):
        s_, start = st
        u = jax.lax.dynamic_slice(esrc_p, (start,), (B,))
        v = jax.lax.dynamic_slice(edst_p, (start,), (B,))
        ok = (start + jnp.arange(B, dtype=jnp.int32)) < n_valid
        reach = jax.vmap(
            lambda uu, vv: _within_k_sparse(s_.nbr, uu, vv, k, frontier_cap)
        )(u, v)
        take = ok & (u != v) & ~reach
        nbr, deg, dover = s_.nbr, s_.deg, s_.deg_overflow
        for a, b in ((u, v), (v, u)):
            nbr, deg, dover = _row_append_batch(
                nbr, deg, dover, a, b, take, D
            )
        # Batched edge-list append in candidate order.
        pos = s_.n + jnp.cumsum(take.astype(jnp.int32)).astype(jnp.int32) - 1
        store = take & (pos < s_.esrc.shape[0])
        tgt = jnp.where(store, pos, s_.esrc.shape[0])
        esrc2 = s_.esrc.at[tgt].set(u, mode="drop")
        edst2 = s_.edst.at[tgt].set(v, mode="drop")
        overflow = s_.overflow | jnp.any(take & ~store)
        return SparseSpannerSummary(
            nbr, deg, esrc2, edst2,
            s_.n + jnp.sum(take).astype(jnp.int32), overflow, dover,
        ), start + B

    out, _ = jax.lax.while_loop(cond, body, (s, jnp.int32(0)))
    return out


def sparse_spanner(vertex_capacity: int, k: int, max_degree: int,
                   max_edges: int | None = None,
                   frontier_cap: int | None = None,
                   ingest_combine: bool = False,
                   payload_cap: int | None = None,
                   local_degree: int | None = None,
                   gate_batch: int | None = None) -> SummaryAggregation:
    """k-spanner over a capped-degree adjacency: O(N*D) memory instead of
    the dense path's O(N^2), feasible at N >= 1M. Degree/frontier caps
    degrade conservatively (extra accepted edges, never a broken stretch
    bound); ``deg_overflow`` counts how often that happened.

    ``ingest_combine``: see :func:`spanner` — the chunk-local spanner
    codec (native toolchain required; explicit ``payload_cap``; one more
    k-factor on the stretch bound, as with every merge level). Chunk-local
    degree-cap overflows are folded into ``deg_overflow``.

    ``gate_batch`` (k == 2 only) switches the fold to the batched
    closed-form gate (:func:`_sparse_fold_chunk_k2`): ``gate_batch``
    candidates gate per step via one D x D row intersection each —
    >1M edges/s at n_v = 2^20 on v5e vs ~5k for the per-edge BFS scan.
    Conservative-acceptance semantics (intra-step passers all accepted);
    stretch/subset/connectivity properties hold unchanged."""
    n = vertex_capacity
    D = max_degree
    if gate_batch is not None and k != 2:
        raise ValueError(
            "gate_batch uses the closed-form distance-2 gate; only k == 2 "
            "is supported (general k runs the BFS gate)"
        )
    # A spanner of a connected graph needs up to ~k-spanner-size edges;
    # default to the dense path's 4*n so the sparse scale target (N >= 1M)
    # works out of the box. NOTE: the combine re-gates the smaller list
    # edge-by-edge (CombineSpanners semantics), so its cost scales with
    # max_edges — tighten it when the expected spanner is small.
    e_cap = max_edges if max_edges is not None else 4 * n
    F = frontier_cap if frontier_cap is not None else max(32, 4 * D)

    def init() -> SparseSpannerSummary:
        return SparseSpannerSummary(
            nbr=jnp.full((n, D), -1, jnp.int32),
            deg=jnp.zeros((n,), jnp.int32),
            esrc=jnp.zeros((e_cap,), jnp.int32),
            edst=jnp.zeros((e_cap,), jnp.int32),
            n=jnp.zeros((), jnp.int32),
            overflow=jnp.zeros((), bool),
            deg_overflow=jnp.zeros((), jnp.int32),
        )

    def fold(s, chunk):
        if gate_batch is not None:
            return _sparse_fold_chunk_k2(
                s, chunk.src, chunk.dst, chunk.valid, D, gate_batch
            )
        return _sparse_insert_edges(
            s, chunk.src, chunk.dst, chunk.valid, k, D, F
        )

    def combine(a, b):
        # Merge smaller into larger (CombineSpanners.reduce,
        # Spanner.java:91-116), batch-re-gating the donor's edges: cost ∝
        # the donor's accepted edges (while_loop stops at small.n), not
        # the max_edges lane capacity (VERDICT r3 item 10).
        big = jax.tree.map(lambda x, y: jnp.where(a.n >= b.n, x, y), a, b)
        small = jax.tree.map(lambda x, y: jnp.where(a.n >= b.n, y, x), a, b)
        merged = _sparse_insert_edges_batched(
            big, small.esrc, small.edst, small.n, k, D, F
        )
        return merged._replace(
            overflow=merged.overflow | small.overflow,
            deg_overflow=merged.deg_overflow + small.deg_overflow,
        )

    from ..utils import native

    hc = fc = None
    if ingest_combine:
        if payload_cap is None:
            raise ValueError(
                "ingest_combine requires an explicit payload_cap (bound "
                "the chunk-local spanner size; device re-gate cost and "
                "wire bytes scale with it)"
            )
        if native.available("spanner"):
            def _insert_payload(st, pl):
                out = _sparse_insert_edges(
                    st, pl["src"], pl["dst"], pl["valid"], k, D, F
                )
                return out._replace(
                    deg_overflow=out.deg_overflow + pl["dover"]
                )

            hc, fc = _spanner_codec(
                k, payload_cap, n,
                local_degree if local_degree is not None else max(128, D),
                _insert_payload,
            )
    return SummaryAggregation(
        init=init,
        fold=fold,
        combine=combine,
        transform=None,
        host_compress=hc,
        fold_compressed=fc,
        name=f"sparse-spanner-k{k}",
    )


def _spanner_codec(k: int, payload_cap: int, n_v: int, local_degree: int,
                   insert_fn):
    """(host_compress, fold_compressed) for the spanner ingest codec.

    ``host_compress`` reduces each chunk to its CHUNK-LOCAL spanner via the
    native kernel (fresh logical state per chunk; buffers are per-thread
    and reused — the prefetch pool may compress chunks concurrently) so
    the device re-gates only those edges: the reference's per-partition
    fold relocated to the ingest side (SummaryBulkAggregation.java:76-80),
    with the device fold playing CombineSpanners (Spanner.java:91-116).
    Each re-gate level relaxes the stretch bound by a factor of k, exactly
    like the reference's own merge levels.

    The payload also carries the chunk's local degree-cap overflow count
    so sparse summaries keep their ``deg_overflow`` accounting honest
    (``insert_fn`` decides whether to consume it).
    """
    import threading

    from ..utils.native import spanner_chunk_fold

    tls = threading.local()

    def host_compress(chunk):
        h = chunk.to_numpy()
        st = getattr(tls, "st", None)
        if st is None:
            st = tls.st = {
                "nbr": np.full((n_v, local_degree), -1, np.int32),
                "deg": np.zeros((n_v,), np.int32),
                "stamp": np.zeros((n_v,), np.int32),
                "meta": np.zeros((3,), np.int64),
            }
        # Per-chunk logical reset without touching the big buffers: rows
        # past deg[u] are never read, and the stamp epoch (meta[0])
        # persists across chunks by design.
        st["deg"][:] = 0
        st["meta"][1] = 0
        dover0 = int(st["meta"][2])
        psrc = np.zeros((payload_cap,), np.int32)
        pdst = np.zeros((payload_cap,), np.int32)
        try:
            spanner_chunk_fold(
                h.src, h.dst, h.valid, n_v, k, local_degree,
                st["nbr"], st["deg"], st["stamp"], st["meta"], psrc, pdst,
            )
        except ValueError as e:
            if "overflow" in str(e):
                raise ValueError(
                    f"chunk-local spanner exceeded payload_cap="
                    f"{payload_cap}; raise it (or disable ingest_combine)"
                ) from e
            raise
        m = int(st["meta"][1])
        pvalid = np.zeros((payload_cap,), bool)
        pvalid[:m] = True
        return {
            "src": psrc, "dst": pdst, "valid": pvalid,
            "dover": np.int32(int(st["meta"][2]) - dover0),
        }

    def fold_compressed(s, payload):
        # payload leaves are [K, ...]: re-gate each chunk-local spanner
        # into the global one, in batch order (CombineSpanners semantics).
        def body(st, pl):
            return insert_fn(st, pl), None

        out, _ = jax.lax.scan(body, s, payload)
        return out

    return host_compress, fold_compressed


def spanner(vertex_capacity: int, k: int,
            max_edges: int | None = None,
            max_degree: int | None = None,
            ingest_combine: bool = False,
            payload_cap: int | None = None,
            local_degree: int = 128,
            gate_batch: int | None = None) -> SummaryAggregation:
    """Build the k-spanner aggregation (Spanner.java ctor takes
    (mergeWindowTime, k); the merge cadence is the runner's merge_every /
    window_ms here). ``max_degree`` switches to the capped-degree sparse
    summary (the N >= 1M path).

    ``ingest_combine`` (opt-in; needs the native toolchain and an
    explicit ``payload_cap``) attaches the spanner codec: each chunk
    pre-reduces on the host to its chunk-local spanner and the device
    re-gates only those edges — the per-edge k-hop check (the dominant
    device cost) then runs over ``payload_cap`` lanes instead of the
    whole chunk (~5x measured on a 40k-edge/512-vertex Zipf stream; the
    win scales with chunk_size / payload_cap, so size ``payload_cap`` to
    the expected chunk-local spanner, NOT to max_edges). Each re-gate
    level relaxes the stretch bound by a factor of k (chunk-local gate,
    shard combine, window merge each count one level), the same
    degradation as the reference's own parallel plan — hence opt-in. For
    a centralized pipeline :class:`HostSpannerStream` is faster still
    (exact k-stretch, no device). The chunk-local adjacency caps rows at
    ``local_degree`` (conservative: overflows only ADD edges).
    """
    if max_degree is not None:
        return sparse_spanner(vertex_capacity, k, max_degree, max_edges,
                              ingest_combine=ingest_combine,
                              payload_cap=payload_cap,
                              local_degree=local_degree,
                              gate_batch=gate_batch)
    n = vertex_capacity
    e_cap = max_edges if max_edges is not None else 4 * n
    if ingest_combine and payload_cap is None:
        raise ValueError(
            "ingest_combine requires an explicit payload_cap (bound the "
            "chunk-local spanner size; device re-gate cost and wire bytes "
            "scale with it)"
        )

    def init() -> SpannerSummary:
        return SpannerSummary(
            adj=jnp.zeros((n, n), bool),
            esrc=jnp.zeros((e_cap,), jnp.int32),
            edst=jnp.zeros((e_cap,), jnp.int32),
            n=jnp.zeros((), jnp.int32),
            overflow=jnp.zeros((), bool),
        )

    def fold(s: SpannerSummary, chunk) -> SpannerSummary:
        return _insert_edges(s, chunk.src, chunk.dst, chunk.valid, k)


    def combine(a: SpannerSummary, b: SpannerSummary) -> SpannerSummary:
        # Merge smaller into larger (CombineSpanners.reduce,
        # Spanner.java:91-116), batch-re-gating the donor's edges — cost
        # ∝ the donor's accepted edges, not the lane capacity (VERDICT
        # r3 item 10; same batch semantics as the sparse combine).
        big, small = jax.tree.map(
            lambda x, y: jnp.where(a.n >= b.n, x, y), a, b
        ), jax.tree.map(lambda x, y: jnp.where(a.n >= b.n, y, x), a, b)
        merged = _insert_edges_batched(big, small.esrc, small.edst,
                                       small.n, k)
        return merged._replace(overflow=merged.overflow | small.overflow)

    from ..utils import native

    hc = fc = None
    if ingest_combine and native.available("spanner"):
        # The dense summary has no deg_overflow field; the chunk-local
        # degree cap is conservative (extra accepted edges only) and its
        # count is dropped here — the sparse path keeps it.
        hc, fc = _spanner_codec(
            k, payload_cap, n, local_degree,
            lambda st, pl: _insert_edges(
                st, pl["src"], pl["dst"], pl["valid"], k
            ),
        )
    return SummaryAggregation(
        init=init,
        fold=fold,
        combine=combine,
        transform=None,
        host_compress=hc,
        fold_compressed=fc,
        name=f"spanner-k{k}",
    )


def spanner_query(vertex_capacity: int, k: int, *, name: str = "spanner",
                  every: int = 1, max_edges: int | None = None,
                  max_degree: int | None = None,
                  gate_batch: int | None = None):
    """Fuse-compatible k-spanner query (``engine.multiquery.fuse``).

    The spanner is the one non-accumulating plan of the library quartet:
    its cross-window merge is the reference's ``CombineSpanners``
    re-gate, so the fused plan carries ``{local, global}`` sub-state and
    runs that merge INSIDE the fused fold as a masked no-op sub-fold
    firing every ``every`` chunks — the per-query merge window. Its
    emission is ``combine(local, global)`` (merge-on-read), so the
    window tail is always included, exactly matching the standalone
    plan's close-at-emission semantics."""
    from ..engine.multiquery import QuerySpec

    return QuerySpec(
        name=name,
        agg=spanner(vertex_capacity, k, max_edges=max_edges,
                    max_degree=max_degree, gate_batch=gate_batch),
        every=every,
        slot_capacity=vertex_capacity,
    )


class HostSpannerStream:
    """Centralized native host spanner — the fast path for the
    order-dependent fold (the weighted-matching precedent: a strictly
    sequential scalar state machine runs ~1000x faster as a native host
    stage than as a per-edge device scan; measured 4.9k edges/s dense /
    0.4k sparse on device vs multi-M edges/s here).

    With ``max_degree`` at least the spanner's true max degree the accepted
    edge list equals the dense device path's exactly (same stream order,
    same gate). Under a binding degree cap both this and
    :func:`sparse_spanner` degrade conservatively (extra accepted edges,
    never a broken stretch bound) but not identically: the device sparse
    gate also bounds its BFS frontier (``frontier_cap``), which can
    under-report reachability in cases this exact bounded BFS does not.
    """

    def __init__(self, stream, k: int, max_degree: int = 64,
                 max_edges: int | None = None):
        from ..utils import native

        if not native.available("spanner"):
            raise RuntimeError(
                "native spanner kernel unavailable (no toolchain); use "
                "spanner()/sparse_spanner() through stream.aggregate()"
            )
        self.stream = stream
        self.k = k
        self.max_degree = max_degree
        n = stream.ctx.vertex_capacity
        self.e_cap = max_edges if max_edges is not None else 4 * n
        self._nbr = np.full((n, max_degree), -1, np.int32)
        self._deg = np.zeros((n,), np.int32)
        self._stamp = np.zeros((n,), np.int32)
        self._meta = np.zeros((3,), np.int64)
        self._esrc = np.zeros((self.e_cap,), np.int32)
        self._edst = np.zeros((self.e_cap,), np.int32)
        self._drained = False
        self._failed: Exception | None = None

    def _drain(self):
        if self._drained:
            return
        if self._failed is not None:
            # A partial fold corrupted nothing, but re-draining would:
            # EdgeStream.__iter__ restarts the stream, and re-folding it
            # into the already-populated state double-inserts. Fail fast.
            raise RuntimeError(
                "spanner fold previously failed; build a new "
                "HostSpannerStream (with a larger max_edges) and re-run"
            ) from self._failed
        from ..utils.native import spanner_chunk_fold

        n = self.stream.ctx.vertex_capacity
        try:
            for c in self.stream:
                h = c.to_numpy()
                spanner_chunk_fold(
                    h.src, h.dst, h.valid, n, self.k, self.max_degree,
                    self._nbr, self._deg, self._stamp, self._meta,
                    self._esrc, self._edst,
                )
        except Exception as e:
            self._failed = e
            raise
        self._drained = True

    @property
    def deg_overflow(self) -> int:
        """Row inserts dropped by the degree cap (each can only make the
        spanner accept extra edges, never break the stretch bound)."""
        self._drain()
        return int(self._meta[2])

    def final_edges(self) -> list[tuple[int, int]]:
        """Accepted edges as raw-id pairs, insertion order."""
        self._drain()
        m = int(self._meta[1])
        src = self.stream.ctx.decode(self._esrc[:m])
        dst = self.stream.ctx.decode(self._edst[:m])
        return list(zip(src.tolist(), dst.tolist()))


def host_spanner(stream, k: int, max_degree: int = 64,
                 max_edges: int | None = None) -> HostSpannerStream:
    return HostSpannerStream(stream, k, max_degree, max_edges)


def spanner_edges(summary, ctx) -> list[tuple[int, int]]:
    """Decode the accepted edge list to raw-id pairs (the reference's
    flattened adjacency printout, SpannerExample.java:139-153).

    Pairs are set-deduped: the sparse path can re-take an edge whose row
    inserts were dropped by the degree cap (the adjacency then under-
    reports reachability — conservative), so the list may hold repeats of
    the same undirected pair; the spanner is its edge *set*.
    """
    if bool(summary.overflow):
        raise RuntimeError("spanner edge list overflowed; raise max_edges")
    m = int(summary.n)
    src = np.asarray(summary.esrc[:m])
    dst = np.asarray(summary.edst[:m])
    lo = np.minimum(src, dst)
    hi = np.maximum(src, dst)
    _, first = np.unique(lo.astype(np.int64) * (1 << 32) + hi,
                         return_index=True)
    keep = np.sort(first)  # preserve insertion order
    src = ctx.decode(src[keep])
    dst = ctx.decode(dst[keep])
    return list(zip(src.tolist(), dst.tolist()))
