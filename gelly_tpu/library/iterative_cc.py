"""Iterative Connected Components — the feedback-loop runtime pattern.

TPU-native re-design of ``M/example/IterativeConnectedComponents.java:43-229``:
the reference uses a Flink streaming iteration (``edges.iterate()`` +
``closeWith``) whose stateful body merges component sets per edge and feeds
``(vertex, componentId)`` updates back into the loop. The idiomatic XLA
equivalent of that asynchronous feedback channel is a per-chunk
**min-label-propagation fixpoint**: a ``lax.while_loop`` scatters each
edge's minimum endpoint label to both endpoints until no label changes —
same final labels (component id = minimum vertex in the component, as the
reference converges to), no feedback queue.

This is deliberately a second, mechanism-distinct CC implementation next to
the union-find aggregation (the reference also ships both); tests assert
they agree.
"""

from __future__ import annotations

from typing import Iterator

import jax
import jax.numpy as jnp

from ..core.stream import Update
from ..ops import segments


@jax.jit
def _propagate(labels, seen, chunk):
    seen = segments.mark_seen(seen, chunk.src, chunk.valid)
    seen = segments.mark_seen(seen, chunk.dst, chunk.valid)

    def body(lab):
        m = jnp.minimum(lab[chunk.src], lab[chunk.dst])
        lab2 = segments.masked_scatter_min(lab, chunk.src, m, chunk.valid)
        lab2 = segments.masked_scatter_min(lab2, chunk.dst, m, chunk.valid)
        # Label-pointer chase: lab[x] = y asserts x~y, so folding in
        # lab[lab] relabels members of components merged by EARLIER chunks
        # (the feedback channel's transitive relabeling, without which a
        # vertex absent from this chunk would keep its stale label).
        return jnp.minimum(lab2, lab2[lab2])

    def cond_changed(state):
        lab, changed = state
        return changed

    def step(state):
        lab, _ = state
        lab2 = body(lab)
        return lab2, jnp.any(lab2 != lab)

    labels, _ = jax.lax.while_loop(
        cond_changed, step, (labels, jnp.bool_(True))
    )
    return labels, seen


class IterativeCCStream:
    """Per-chunk (vertex, label) updates; labels improve monotonically as
    more edges arrive — the reference's continuously-emitted relabels."""

    def __init__(self, stream):
        self.stream = stream

    def __iter__(self) -> Iterator[Update]:
        n = self.stream.ctx.vertex_capacity
        labels = jnp.arange(n, dtype=jnp.int32)
        seen = jnp.zeros((n,), bool)
        for c in self.stream:
            labels, seen = _propagate(labels, seen, c)
            ids = jnp.concatenate([c.src, c.dst])
            ok = jnp.concatenate([c.valid, c.valid])
            yield Update(ids, labels[ids], ok)

    def final_labels(self):
        labels = None
        n = self.stream.ctx.vertex_capacity
        lab = jnp.arange(n, dtype=jnp.int32)
        seen = jnp.zeros((n,), bool)
        for c in self.stream:
            lab, seen = _propagate(lab, seen, c)
        return jnp.where(seen, lab, -1)
