from .bipartiteness import (
    BipartitenessResult,
    bipartiteness_check,
    to_candidates,
)
from .connected_components import (
    CCSummary,
    connected_components,
    connected_components_tree,
    labels_to_components,
)
