from .bipartiteness import (
    BipartitenessResult,
    bipartiteness_check,
    bipartiteness_query,
    to_candidates,
)
from .connected_components import (
    CCSummary,
    cc_query,
    connected_components,
    connected_components_tree,
    labels_to_components,
)
from .degrees import (
    degree_aggregate,
    degree_distribution,
    degrees_query,
    sharded_degrees,
)
from .iterative_cc import IterativeCCStream
from .matching import weighted_matching
from .spanner import host_spanner, spanner, spanner_edges, spanner_query
from .triangles import (
    exact_triangle_count,
    sampled_triangle_count,
    window_triangles,
)
