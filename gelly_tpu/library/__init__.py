from .connected_components import (
    CCSummary,
    connected_components,
    connected_components_tree,
    labels_to_components,
)
