// Native ingest-side chunk combiners.
//
// The reference pre-aggregates per partition before the global merge
// (SummaryBulkAggregation's per-partition window fold,
// /root/reference/src/main/java/org/apache/flink/graph/streaming/SummaryBulkAggregation.java:76-80,
// folding DisjointSet.union per edge, .../summaries/DisjointSet.java:92-118).
// On TPU the ingest link (host->device) is the scarce resource, so the same
// partial aggregation runs *before* the transfer: a chunk of E edges is
// reduced to its spanning forest — at most min(E, n_v) (vertex, root) pairs,
// shipped as a dense i32 label array. Connectivity is preserved exactly;
// bytes-per-edge on the wire drops by 1-2 orders of magnitude.
//
// Exposed via ctypes (gelly_tpu/utils/native.py); no pybind dependency.

#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

// Find with path halving: keeps trees near-flat without recursion.
inline int32_t find_root(int32_t* p, int32_t x) {
  while (p[x] != x) {
    p[x] = p[p[x]];
    x = p[x];
  }
  return x;
}

// Parity-carrying find with parity-aware path halving: before hopping to
// the grandparent, fold the parent's edge parity into this node's so
// parity[x] always means "to labels[x]". Without halving, union-by-min
// grows long chains on skewed streams (~6x slower). *p_out receives the
// parity from x to its root.
inline int32_t parity_find(int32_t* labels, uint8_t* parity, int32_t x,
                           uint8_t* p_out) {
  uint8_t acc = 0;
  while (labels[x] != x) {
    const int32_t par = labels[x];
    if (labels[par] != par) {
      parity[x] = static_cast<uint8_t>(parity[x] ^ parity[par]);
      labels[x] = labels[par];
    }
    acc ^= parity[x];
    x = labels[x];
  }
  *p_out = acc;
  return x;
}

}  // namespace

extern "C" {

// Union-find over one chunk's valid edges.
//
//   labels[v] = component root slot for every vertex touched by the chunk,
//   labels[v] = -1 for untouched slots.
//
// Roots are canonicalized to the minimum slot in the chunk-local component,
// matching the device kernel's min-root convention
// (gelly_tpu/ops/unionfind.py) so the downstream union of (v, labels[v])
// star edges is already near-flat.
//
// Returns 0 on success, 2 if any valid edge has a slot outside [0, n_v).
int cc_chunk_combine(const int32_t* src, const int32_t* dst,
                     const uint8_t* valid, int64_t n, int32_t n_v,
                     int32_t* labels) {
  // labels doubles as the parent array during the pass.
  std::memset(labels, 0xff, sizeof(int32_t) * static_cast<size_t>(n_v));
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) continue;
    const int32_t u = src[i];
    const int32_t v = dst[i];
    if (u < 0 || u >= n_v || v < 0 || v >= n_v) return 2;
    if (labels[u] < 0) labels[u] = u;
    if (labels[v] < 0) labels[v] = v;
    const int32_t ru = find_root(labels, u);
    const int32_t rv = find_root(labels, v);
    if (ru != rv) {
      // Union by min root: canonical representative = min slot.
      if (ru < rv) {
        labels[rv] = ru;
      } else {
        labels[ru] = rv;
      }
    }
  }
  // Flatten: every touched vertex points directly at its root.
  for (int32_t v = 0; v < n_v; ++v) {
    if (labels[v] >= 0) labels[v] = find_root(labels, v);
  }
  return 0;
}

// Parity (bipartiteness) variant: same spanning-forest compression but each
// vertex also carries the XOR parity of its path to the root — enough to
// reconstruct the 2-coloring constraints of the chunk (the Candidates sign
// logic, .../summaries/Candidates.java:61-74). An odd cycle inside the chunk
// sets *conflict to 1 (the chunk alone is non-bipartite).
//
//   labels[v]  = root slot or -1
//   parity[v]  = path parity to root (0/1), valid where labels[v] >= 0
int parity_chunk_combine(const int32_t* src, const int32_t* dst,
                         const uint8_t* valid, int64_t n, int32_t n_v,
                         int32_t* labels, uint8_t* parity,
                         int32_t* conflict) {
  std::memset(labels, 0xff, sizeof(int32_t) * static_cast<size_t>(n_v));
  std::memset(parity, 0, static_cast<size_t>(n_v));
  *conflict = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) continue;
    const int32_t u = src[i];
    const int32_t v = dst[i];
    if (u < 0 || u >= n_v || v < 0 || v >= n_v) return 2;
    if (labels[u] < 0) { labels[u] = u; parity[u] = 0; }
    if (labels[v] < 0) { labels[v] = v; parity[v] = 0; }
    uint8_t pu, pv;
    const int32_t ru = parity_find(labels, parity, u, &pu);
    const int32_t rv = parity_find(labels, parity, v, &pv);
    if (ru == rv) {
      if (pu == pv) *conflict = 1;  // odd cycle
      continue;
    }
    if (ru < rv) {
      labels[rv] = ru;
      parity[rv] = static_cast<uint8_t>(pu ^ pv ^ 1);
    } else {
      labels[ru] = rv;
      parity[ru] = static_cast<uint8_t>(pu ^ pv ^ 1);
    }
  }
  // Flatten labels and parities together (two passes of pointer chase are
  // bounded by tree height; height is small after union-by-min + the
  // root-ward writes above, and this pass fully flattens).
  for (int32_t v = 0; v < n_v; ++v) {
    if (labels[v] < 0) continue;
    int32_t r = v; uint8_t p = 0;
    while (labels[r] != r) { p ^= parity[r]; r = labels[r]; }
    labels[v] = r;
    parity[v] = p;
  }
  return 0;
}

// Degree-delta codec: one pass over the chunk accumulating the ±1 endpoint
// deltas (EventType deletions subtract) into a dense i32[n_v] vector — the
// degree equivalent of the forest payloads above (DegreeMapFunction
// semantics, .../SimpleEdgeStream.java:461-478, with DegreeDistribution's
// ±1 deletion handling, .../example/DegreeDistribution.java:70-79). The
// n_v-sized delta vector is what ships over the wire instead of the edges.
//
//   event : optional i8[n] (null = all additions), 1 = deletion
//   valid : optional u8[n] mask (null = all valid)
//
// Returns 0 on success, 2 on a slot outside [0, n_v).
int degree_chunk_deltas(const int32_t* src, const int32_t* dst,
                        const int8_t* event, const uint8_t* valid,
                        int64_t n, int32_t n_v, int32_t count_out,
                        int32_t count_in, int32_t* out) {
  std::memset(out, 0, sizeof(int32_t) * static_cast<size_t>(n_v));
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) continue;
    const int32_t d = (event != nullptr && event[i] == 1) ? -1 : 1;
    if (count_out) {
      const int32_t u = src[i];
      if (u < 0 || u >= n_v) return 2;
      out[u] += d;
    }
    if (count_in) {
      const int32_t v = dst[i];
      if (v < 0 || v >= n_v) return 2;
      out[v] += d;
    }
  }
  return 0;
}

}  // extern "C"

// ------------------------------------------------------------------ //
// Sparse (touched-slot) codecs — the large-n_v path.
//
// The dense combiners above cost O(n_v) per chunk (memset + flatten scan)
// and ship n_v-proportional payloads; at Twitter-2010-class n_v (~2^26)
// that inverts the wire compression (256 MB per chunk payload). These
// variants instead run the same union-find over a chunk-local
// open-addressed hash of the touched vertices — O(E) time and memory
// regardless of n_v, the C++ analog of the reference's per-subtask
// HashMap partial fold whose state is proportional to *touched* keys
// (SummaryBulkAggregation.java:109-130) — and emit counted
// (vertex, root) pairs. Payload bytes ∝ min(2E, touched), never n_v.

namespace {

// Chunk-local vertex interning: open addressing, linear probing, load
// factor <= 0.5. Entries index the parallel vert[]/parent[] arrays.
struct LocalTable {
  int32_t* table = nullptr;  // table[i] = local index or -1
  int32_t* vert = nullptr;   // vert[local] = global vertex slot
  int32_t* parent = nullptr; // union-find over local indices
  int64_t mask = 0;
  int32_t count = 0;

  bool init(int64_t n_edges) {
    const int64_t cap = 2 * (n_edges > 0 ? n_edges : 1);
    int64_t tsize = 4;
    while (tsize < 2 * cap) tsize <<= 1;  // >= 2x entries: load <= 0.5
    table = static_cast<int32_t*>(std::malloc(tsize * sizeof(int32_t)));
    vert = static_cast<int32_t*>(std::malloc(cap * sizeof(int32_t)));
    parent = static_cast<int32_t*>(std::malloc(cap * sizeof(int32_t)));
    if (!table || !vert || !parent) return false;
    std::memset(table, 0xff, tsize * sizeof(int32_t));
    mask = tsize - 1;
    return true;
  }

  ~LocalTable() {
    std::free(table);
    std::free(vert);
    std::free(parent);
  }

  // Local index of v, interning on first sight (parent = self).
  inline int32_t intern(int32_t v) {
    int64_t i = (static_cast<uint32_t>(v) * 2654435761u) & mask;
    while (true) {
      const int32_t e = table[i];
      if (e < 0) {
        table[i] = count;
        vert[count] = v;
        parent[count] = count;
        return count++;
      }
      if (vert[e] == v) return e;
      i = (i + 1) & mask;
    }
  }
};

}  // namespace

extern "C" {

// Sparse spanning-forest codec: counted (vertex, root) pairs of one
// chunk's touched vertices. Roots are canonicalized to the minimum
// global slot in the chunk-local component (matching cc_chunk_combine's
// min-root convention). out_v/out_r need capacity >= 2 * n (worst case:
// every edge touches two fresh vertices).
//
// Returns the pair count (>= 0), -2 on a slot outside [0, n_v), -3 if
// cap_pairs is too small, -4 on allocation failure.
int64_t cc_chunk_combine_sparse(const int32_t* src, const int32_t* dst,
                                const uint8_t* valid, int64_t n,
                                int32_t n_v, int32_t* out_v,
                                int32_t* out_r, int64_t cap_pairs) {
  LocalTable t;
  if (!t.init(n)) return -4;
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) continue;
    const int32_t u = src[i];
    const int32_t v = dst[i];
    if (u < 0 || u >= n_v || v < 0 || v >= n_v) return -2;
    const int32_t lu = t.intern(u);
    const int32_t lv = t.intern(v);
    const int32_t ru = find_root(t.parent, lu);
    const int32_t rv = find_root(t.parent, lv);
    if (ru != rv) {
      // Union by min global slot: canonical representative.
      if (t.vert[ru] < t.vert[rv]) {
        t.parent[rv] = ru;
      } else {
        t.parent[ru] = rv;
      }
    }
  }
  if (t.count > cap_pairs) return -3;
  for (int32_t j = 0; j < t.count; ++j) {
    out_v[j] = t.vert[j];
    out_r[j] = t.vert[find_root(t.parent, j)];
  }
  return t.count;
}

// Root-indexed variant for the compact-space codec: identical to
// cc_chunk_combine_sparse, except the root is ALSO reported as its output
// position (out_ri[j] = index k with out_v[k] == root of out_v[j]). The
// root's local id doubles as its output slot here, so the index costs
// nothing extra — and it saves the device fold a whole pointer chase per
// pair (rv = chased_roots[ri] instead of re-chasing the root id).
int64_t cc_chunk_combine_sparse_idx(const int32_t* src, const int32_t* dst,
                                    const uint8_t* valid, int64_t n,
                                    int32_t n_v, int32_t* out_v,
                                    int32_t* out_r, int32_t* out_ri,
                                    int64_t cap_pairs) {
  LocalTable t;
  if (!t.init(n)) return -4;
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) continue;
    const int32_t u = src[i];
    const int32_t v = dst[i];
    if (u < 0 || u >= n_v || v < 0 || v >= n_v) return -2;
    const int32_t lu = t.intern(u);
    const int32_t lv = t.intern(v);
    const int32_t ru = find_root(t.parent, lu);
    const int32_t rv = find_root(t.parent, lv);
    if (ru != rv) {
      if (t.vert[ru] < t.vert[rv]) {
        t.parent[rv] = ru;
      } else {
        t.parent[ru] = rv;
      }
    }
  }
  if (t.count > cap_pairs) return -3;
  for (int32_t j = 0; j < t.count; ++j) {
    const int32_t r = find_root(t.parent, j);
    out_v[j] = t.vert[j];
    out_r[j] = t.vert[r];
    out_ri[j] = r;
  }
  return t.count;
}

// Sparse parity (bipartiteness) codec: (vertex, root, parity) triples plus
// a chunk-local odd-cycle flag. Same contract as cc_chunk_combine_sparse
// with out_p[j] = 2-coloring parity of out_v[j] relative to out_r[j].
int64_t parity_chunk_combine_sparse(const int32_t* src, const int32_t* dst,
                                    const uint8_t* valid, int64_t n,
                                    int32_t n_v, int32_t* out_v,
                                    int32_t* out_r, uint8_t* out_p,
                                    int32_t* conflict, int64_t cap_pairs) {
  LocalTable t;
  if (!t.init(n)) return -4;
  const int64_t cap = 2 * (n > 0 ? n : 1);
  uint8_t* parity = static_cast<uint8_t*>(std::malloc(cap));
  if (!parity) return -4;
  *conflict = 0;
  int64_t ret = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) continue;
    const int32_t u = src[i];
    const int32_t v = dst[i];
    if (u < 0 || u >= n_v || v < 0 || v >= n_v) { ret = -2; break; }
    int32_t before = t.count;
    const int32_t lu = t.intern(u);
    if (t.count != before) parity[lu] = 0;  // fresh entry seeds parity 0
    before = t.count;
    const int32_t lv = t.intern(v);
    if (t.count != before) parity[lv] = 0;
    uint8_t pu, pv;
    const int32_t ru = parity_find(t.parent, parity, lu, &pu);
    const int32_t rv = parity_find(t.parent, parity, lv, &pv);
    if (ru == rv) {
      if (pu == pv) *conflict = 1;  // odd cycle inside the chunk
      continue;
    }
    if (t.vert[ru] < t.vert[rv]) {
      t.parent[rv] = ru;
      parity[rv] = static_cast<uint8_t>(pu ^ pv ^ 1);
    } else {
      t.parent[ru] = rv;
      parity[ru] = static_cast<uint8_t>(pu ^ pv ^ 1);
    }
  }
  if (ret == 0) {
    if (t.count > cap_pairs) {
      ret = -3;
    } else {
      for (int32_t j = 0; j < t.count; ++j) {
        int32_t r = j;
        uint8_t p = 0;
        while (t.parent[r] != r) {
          p ^= parity[r];
          r = t.parent[r];
        }
        out_v[j] = t.vert[j];
        out_r[j] = t.vert[r];
        out_p[j] = p;
      }
      ret = t.count;
    }
  }
  std::free(parity);
  return ret;
}

// Sparse degree-delta codec: counted (vertex, net-delta) pairs; zero net
// deltas (an addition cancelled by a deletion within the chunk) are
// omitted. out arrays need capacity >= 2 * n.
int64_t degree_chunk_deltas_sparse(const int32_t* src, const int32_t* dst,
                                   const int8_t* event,
                                   const uint8_t* valid, int64_t n,
                                   int32_t n_v, int32_t count_out,
                                   int32_t count_in, int32_t* out_v,
                                   int32_t* out_d, int64_t cap_pairs) {
  LocalTable t;
  if (!t.init(n)) return -4;
  // Reuse parent[] as the delta accumulator (the table does no unions).
  int32_t* acc = t.parent;
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) continue;
    const int32_t d = (event != nullptr && event[i] == 1) ? -1 : 1;
    if (count_out) {
      const int32_t u = src[i];
      if (u < 0 || u >= n_v) return -2;
      const int32_t before = t.count;
      const int32_t lu = t.intern(u);
      if (t.count != before) acc[lu] = 0;  // fresh entry: zero the delta
      acc[lu] += d;
    }
    if (count_in) {
      const int32_t v = dst[i];
      if (v < 0 || v >= n_v) return -2;
      const int32_t before = t.count;
      const int32_t lv = t.intern(v);
      if (t.count != before) acc[lv] = 0;
      acc[lv] += d;
    }
  }
  int64_t k = 0;
  for (int32_t j = 0; j < t.count; ++j) {
    if (acc[j] == 0) continue;
    if (k >= cap_pairs) return -3;
    out_v[k] = t.vert[j];
    out_d[k] = acc[j];
    ++k;
  }
  return k;
}

}  // extern "C"
