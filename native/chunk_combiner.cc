// Native ingest-side chunk combiners.
//
// The reference pre-aggregates per partition before the global merge
// (SummaryBulkAggregation's per-partition window fold,
// /root/reference/src/main/java/org/apache/flink/graph/streaming/SummaryBulkAggregation.java:76-80,
// folding DisjointSet.union per edge, .../summaries/DisjointSet.java:92-118).
// On TPU the ingest link (host->device) is the scarce resource, so the same
// partial aggregation runs *before* the transfer: a chunk of E edges is
// reduced to its spanning forest — at most min(E, n_v) (vertex, root) pairs,
// shipped as a dense i32 label array. Connectivity is preserved exactly;
// bytes-per-edge on the wire drops by 1-2 orders of magnitude.
//
// Exposed via ctypes (gelly_tpu/utils/native.py); no pybind dependency.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <new>

namespace {

// Find with path halving: keeps trees near-flat without recursion.
inline int32_t find_root(int32_t* p, int32_t x) {
  while (p[x] != x) {
    p[x] = p[p[x]];
    x = p[x];
  }
  return x;
}

// Parity-carrying find with parity-aware path halving: before hopping to
// the grandparent, fold the parent's edge parity into this node's so
// parity[x] always means "to labels[x]". Without halving, union-by-min
// grows long chains on skewed streams (~6x slower). *p_out receives the
// parity from x to its root.
inline int32_t parity_find(int32_t* labels, uint8_t* parity, int32_t x,
                           uint8_t* p_out) {
  uint8_t acc = 0;
  while (labels[x] != x) {
    const int32_t par = labels[x];
    if (labels[par] != par) {
      parity[x] = static_cast<uint8_t>(parity[x] ^ parity[par]);
      labels[x] = labels[par];
    }
    acc ^= parity[x];
    x = labels[x];
  }
  *p_out = acc;
  return x;
}

}  // namespace

extern "C" {

// Union-find over one chunk's valid edges.
//
//   labels[v] = component root slot for every vertex touched by the chunk,
//   labels[v] = -1 for untouched slots.
//
// Roots are canonicalized to the minimum slot in the chunk-local component,
// matching the device kernel's min-root convention
// (gelly_tpu/ops/unionfind.py) so the downstream union of (v, labels[v])
// star edges is already near-flat.
//
// Returns 0 on success, 2 if any valid edge has a slot outside [0, n_v).
int cc_chunk_combine(const int32_t* src, const int32_t* dst,
                     const uint8_t* valid, int64_t n, int32_t n_v,
                     int32_t* labels) {
  // labels doubles as the parent array during the pass.
  std::memset(labels, 0xff, sizeof(int32_t) * static_cast<size_t>(n_v));
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) continue;
    const int32_t u = src[i];
    const int32_t v = dst[i];
    if (u < 0 || u >= n_v || v < 0 || v >= n_v) return 2;
    if (labels[u] < 0) labels[u] = u;
    if (labels[v] < 0) labels[v] = v;
    const int32_t ru = find_root(labels, u);
    const int32_t rv = find_root(labels, v);
    if (ru != rv) {
      // Union by min root: canonical representative = min slot.
      if (ru < rv) {
        labels[rv] = ru;
      } else {
        labels[ru] = rv;
      }
    }
  }
  // Flatten: every touched vertex points directly at its root.
  for (int32_t v = 0; v < n_v; ++v) {
    if (labels[v] >= 0) labels[v] = find_root(labels, v);
  }
  return 0;
}

// Parity (bipartiteness) variant: same spanning-forest compression but each
// vertex also carries the XOR parity of its path to the root — enough to
// reconstruct the 2-coloring constraints of the chunk (the Candidates sign
// logic, .../summaries/Candidates.java:61-74). An odd cycle inside the chunk
// sets *conflict to 1 (the chunk alone is non-bipartite).
//
//   labels[v]  = root slot or -1
//   parity[v]  = path parity to root (0/1), valid where labels[v] >= 0
int parity_chunk_combine(const int32_t* src, const int32_t* dst,
                         const uint8_t* valid, int64_t n, int32_t n_v,
                         int32_t* labels, uint8_t* parity,
                         int32_t* conflict) {
  std::memset(labels, 0xff, sizeof(int32_t) * static_cast<size_t>(n_v));
  std::memset(parity, 0, static_cast<size_t>(n_v));
  *conflict = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) continue;
    const int32_t u = src[i];
    const int32_t v = dst[i];
    if (u < 0 || u >= n_v || v < 0 || v >= n_v) return 2;
    if (labels[u] < 0) { labels[u] = u; parity[u] = 0; }
    if (labels[v] < 0) { labels[v] = v; parity[v] = 0; }
    uint8_t pu, pv;
    const int32_t ru = parity_find(labels, parity, u, &pu);
    const int32_t rv = parity_find(labels, parity, v, &pv);
    if (ru == rv) {
      if (pu == pv) *conflict = 1;  // odd cycle
      continue;
    }
    if (ru < rv) {
      labels[rv] = ru;
      parity[rv] = static_cast<uint8_t>(pu ^ pv ^ 1);
    } else {
      labels[ru] = rv;
      parity[ru] = static_cast<uint8_t>(pu ^ pv ^ 1);
    }
  }
  // Flatten labels and parities together (two passes of pointer chase are
  // bounded by tree height; height is small after union-by-min + the
  // root-ward writes above, and this pass fully flattens).
  for (int32_t v = 0; v < n_v; ++v) {
    if (labels[v] < 0) continue;
    int32_t r = v; uint8_t p = 0;
    while (labels[r] != r) { p ^= parity[r]; r = labels[r]; }
    labels[v] = r;
    parity[v] = p;
  }
  return 0;
}

// Degree-delta codec: one pass over the chunk accumulating the ±1 endpoint
// deltas (EventType deletions subtract) into a dense i32[n_v] vector — the
// degree equivalent of the forest payloads above (DegreeMapFunction
// semantics, .../SimpleEdgeStream.java:461-478, with DegreeDistribution's
// ±1 deletion handling, .../example/DegreeDistribution.java:70-79). The
// n_v-sized delta vector is what ships over the wire instead of the edges.
//
//   event : optional i8[n] (null = all additions), 1 = deletion
//   valid : optional u8[n] mask (null = all valid)
//
// Returns 0 on success, 2 on a slot outside [0, n_v).
int degree_chunk_deltas(const int32_t* src, const int32_t* dst,
                        const int8_t* event, const uint8_t* valid,
                        int64_t n, int32_t n_v, int32_t count_out,
                        int32_t count_in, int32_t* out) {
  std::memset(out, 0, sizeof(int32_t) * static_cast<size_t>(n_v));
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) continue;
    const int32_t d = (event != nullptr && event[i] == 1) ? -1 : 1;
    if (count_out) {
      const int32_t u = src[i];
      if (u < 0 || u >= n_v) return 2;
      out[u] += d;
    }
    if (count_in) {
      const int32_t v = dst[i];
      if (v < 0 || v >= n_v) return 2;
      out[v] += d;
    }
  }
  return 0;
}

}  // extern "C"

// ------------------------------------------------------------------ //
// Sparse (touched-slot) codecs — the large-n_v path.
//
// The dense combiners above cost O(n_v) per chunk (memset + flatten scan)
// and ship n_v-proportional payloads; at Twitter-2010-class n_v (~2^26)
// that inverts the wire compression (256 MB per chunk payload). These
// variants instead run the same union-find over a chunk-local
// open-addressed hash of the touched vertices — O(E) time and memory
// regardless of n_v, the C++ analog of the reference's per-subtask
// HashMap partial fold whose state is proportional to *touched* keys
// (SummaryBulkAggregation.java:109-130) — and emit counted
// (vertex, root) pairs. Payload bytes ∝ min(2E, touched), never n_v.

namespace {

// Chunk-local vertex interning: open addressing, linear probing, load
// factor <= 0.5. Entries index the parallel vert[]/parent[] arrays.
struct LocalTable {
  int32_t* table = nullptr;  // table[i] = local index or -1
  int32_t* vert = nullptr;   // vert[local] = global vertex slot
  int32_t* parent = nullptr; // union-find over local indices
  int64_t mask = 0;
  int32_t count = 0;

  bool init(int64_t n_edges) {
    const int64_t cap = 2 * (n_edges > 0 ? n_edges : 1);
    int64_t tsize = 4;
    while (tsize < 2 * cap) tsize <<= 1;  // >= 2x entries: load <= 0.5
    table = static_cast<int32_t*>(std::malloc(tsize * sizeof(int32_t)));
    vert = static_cast<int32_t*>(std::malloc(cap * sizeof(int32_t)));
    parent = static_cast<int32_t*>(std::malloc(cap * sizeof(int32_t)));
    if (!table || !vert || !parent) return false;
    std::memset(table, 0xff, tsize * sizeof(int32_t));
    mask = tsize - 1;
    return true;
  }

  ~LocalTable() {
    std::free(table);
    std::free(vert);
    std::free(parent);
  }

  // Local index of v, interning on first sight (parent = self).
  inline int32_t intern(int32_t v) {
    int64_t i = (static_cast<uint32_t>(v) * 2654435761u) & mask;
    while (true) {
      const int32_t e = table[i];
      if (e < 0) {
        table[i] = count;
        vert[count] = v;
        parent[count] = count;
        return count++;
      }
      if (vert[e] == v) return e;
      i = (i + 1) & mask;
    }
  }
};

}  // namespace

extern "C" {

// Sparse spanning-forest codec: counted (vertex, root) pairs of one
// chunk's touched vertices. Roots are canonicalized to the minimum
// global slot in the chunk-local component (matching cc_chunk_combine's
// min-root convention). out_v/out_r need capacity >= 2 * n (worst case:
// every edge touches two fresh vertices).
//
// Returns the pair count (>= 0), -2 on a slot outside [0, n_v), -3 if
// cap_pairs is too small, -4 on allocation failure.
int64_t cc_chunk_combine_sparse(const int32_t* src, const int32_t* dst,
                                const uint8_t* valid, int64_t n,
                                int32_t n_v, int32_t* out_v,
                                int32_t* out_r, int64_t cap_pairs) {
  LocalTable t;
  if (!t.init(n)) return -4;
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) continue;
    const int32_t u = src[i];
    const int32_t v = dst[i];
    if (u < 0 || u >= n_v || v < 0 || v >= n_v) return -2;
    const int32_t lu = t.intern(u);
    const int32_t lv = t.intern(v);
    const int32_t ru = find_root(t.parent, lu);
    const int32_t rv = find_root(t.parent, lv);
    if (ru != rv) {
      // Union by min global slot: canonical representative.
      if (t.vert[ru] < t.vert[rv]) {
        t.parent[rv] = ru;
      } else {
        t.parent[ru] = rv;
      }
    }
  }
  if (t.count > cap_pairs) return -3;
  for (int32_t j = 0; j < t.count; ++j) {
    out_v[j] = t.vert[j];
    out_r[j] = t.vert[find_root(t.parent, j)];
  }
  return t.count;
}

// Root-indexed variant for the compact-space codec: identical to
// cc_chunk_combine_sparse, except the root is ALSO reported as its output
// position (out_ri[j] = index k with out_v[k] == root of out_v[j]). The
// root's local id doubles as its output slot here, so the index costs
// nothing extra — and it saves the device fold a whole pointer chase per
// pair (rv = chased_roots[ri] instead of re-chasing the root id).
int64_t cc_chunk_combine_sparse_idx(const int32_t* src, const int32_t* dst,
                                    const uint8_t* valid, int64_t n,
                                    int32_t n_v, int32_t* out_v,
                                    int32_t* out_r, int32_t* out_ri,
                                    int64_t cap_pairs) {
  LocalTable t;
  if (!t.init(n)) return -4;
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) continue;
    const int32_t u = src[i];
    const int32_t v = dst[i];
    if (u < 0 || u >= n_v || v < 0 || v >= n_v) return -2;
    const int32_t lu = t.intern(u);
    const int32_t lv = t.intern(v);
    const int32_t ru = find_root(t.parent, lu);
    const int32_t rv = find_root(t.parent, lv);
    if (ru != rv) {
      if (t.vert[ru] < t.vert[rv]) {
        t.parent[rv] = ru;
      } else {
        t.parent[ru] = rv;
      }
    }
  }
  if (t.count > cap_pairs) return -3;
  for (int32_t j = 0; j < t.count; ++j) {
    const int32_t r = find_root(t.parent, j);
    out_v[j] = t.vert[j];
    out_r[j] = t.vert[r];
    out_ri[j] = r;
  }
  return t.count;
}

// Sparse parity (bipartiteness) codec: (vertex, root, parity) triples plus
// a chunk-local odd-cycle flag. Same contract as cc_chunk_combine_sparse
// with out_p[j] = 2-coloring parity of out_v[j] relative to out_r[j].
int64_t parity_chunk_combine_sparse(const int32_t* src, const int32_t* dst,
                                    const uint8_t* valid, int64_t n,
                                    int32_t n_v, int32_t* out_v,
                                    int32_t* out_r, uint8_t* out_p,
                                    int32_t* conflict, int64_t cap_pairs) {
  LocalTable t;
  if (!t.init(n)) return -4;
  const int64_t cap = 2 * (n > 0 ? n : 1);
  uint8_t* parity = static_cast<uint8_t*>(std::malloc(cap));
  if (!parity) return -4;
  *conflict = 0;
  int64_t ret = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) continue;
    const int32_t u = src[i];
    const int32_t v = dst[i];
    if (u < 0 || u >= n_v || v < 0 || v >= n_v) { ret = -2; break; }
    int32_t before = t.count;
    const int32_t lu = t.intern(u);
    if (t.count != before) parity[lu] = 0;  // fresh entry seeds parity 0
    before = t.count;
    const int32_t lv = t.intern(v);
    if (t.count != before) parity[lv] = 0;
    uint8_t pu, pv;
    const int32_t ru = parity_find(t.parent, parity, lu, &pu);
    const int32_t rv = parity_find(t.parent, parity, lv, &pv);
    if (ru == rv) {
      if (pu == pv) *conflict = 1;  // odd cycle inside the chunk
      continue;
    }
    if (t.vert[ru] < t.vert[rv]) {
      t.parent[rv] = ru;
      parity[rv] = static_cast<uint8_t>(pu ^ pv ^ 1);
    } else {
      t.parent[ru] = rv;
      parity[ru] = static_cast<uint8_t>(pu ^ pv ^ 1);
    }
  }
  if (ret == 0) {
    if (t.count > cap_pairs) {
      ret = -3;
    } else {
      for (int32_t j = 0; j < t.count; ++j) {
        int32_t r = j;
        uint8_t p = 0;
        while (t.parent[r] != r) {
          p ^= parity[r];
          r = t.parent[r];
        }
        out_v[j] = t.vert[j];
        out_r[j] = t.vert[r];
        out_p[j] = p;
      }
      ret = t.count;
    }
  }
  std::free(parity);
  return ret;
}

// Sparse degree-delta codec: counted (vertex, net-delta) pairs; zero net
// deltas (an addition cancelled by a deletion within the chunk) are
// omitted. out arrays need capacity >= 2 * n.
int64_t degree_chunk_deltas_sparse(const int32_t* src, const int32_t* dst,
                                   const int8_t* event,
                                   const uint8_t* valid, int64_t n,
                                   int32_t n_v, int32_t count_out,
                                   int32_t count_in, int32_t* out_v,
                                   int32_t* out_d, int64_t cap_pairs) {
  LocalTable t;
  if (!t.init(n)) return -4;
  // Reuse parent[] as the delta accumulator (the table does no unions).
  int32_t* acc = t.parent;
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) continue;
    const int32_t d = (event != nullptr && event[i] == 1) ? -1 : 1;
    if (count_out) {
      const int32_t u = src[i];
      if (u < 0 || u >= n_v) return -2;
      const int32_t before = t.count;
      const int32_t lu = t.intern(u);
      if (t.count != before) acc[lu] = 0;  // fresh entry: zero the delta
      acc[lu] += d;
    }
    if (count_in) {
      const int32_t v = dst[i];
      if (v < 0 || v >= n_v) return -2;
      const int32_t before = t.count;
      const int32_t lv = t.intern(v);
      if (t.count != before) acc[lv] = 0;
      acc[lv] += d;
    }
  }
  int64_t k = 0;
  for (int32_t j = 0; j < t.count; ++j) {
    if (acc[j] == 0) continue;
    if (k >= cap_pairs) return -3;
    out_v[k] = t.vert[j];
    out_d[k] = acc[j];
    ++k;
  }
  return k;
}

}  // extern "C"

// ------------------------------------------------------------------ //
// Persistent compact-id session — the native backing of
// gelly_tpu/ops/compact_space.py's CompactIdSession.
//
// The numpy session kept (id -> cid) as a SORTED array pair: every
// assign with fresh ids rebuilt the whole table (O(known) memmove) plus
// per-id searchsorted probes — measured as THE Twitter-scale ingest
// bottleneck (20.1s of a 36.1s run; the combiner it wraps costs ~4s).
// Here the map is an open-addressing hash table with geometric growth:
// one multiplicative-hash probe per id, O(1) amortized insert, no
// per-call rebuild. This is the same table discipline as the reference's
// per-subtask HashMap state (M/SummaryBulkAggregation.java:109-130),
// owned by the ingest host.

namespace {

struct CompactSession {
  int32_t* table = nullptr;    // open addressing: slot -> cid, or -1
  int32_t* vert_of = nullptr;  // vert_of[cid] = global vertex id
  int64_t tsize = 0;
  int64_t mask = 0;
  int32_t count = 0;
  int32_t capacity = 0;
};

inline int64_t cs_hash(int32_t v, int64_t mask) {
  return (static_cast<uint32_t>(v) * 2654435761u) & mask;
}

// Geometrically grown intern table (vs LocalTable's sized-to-worst-case
// policy): for merge passes whose DISTINCT count is far below the input
// count, growth keeps most probes cache-resident instead of walking a
// DRAM-sized table from the first insert.
struct GrowTable {
  int32_t* table = nullptr;
  int32_t* vert = nullptr;
  int32_t* parent = nullptr;
  int64_t tsize = 0;
  int64_t mask = 0;
  int32_t count = 0;

  bool init(int64_t tsize0) {
    tsize = tsize0;
    mask = tsize - 1;
    table = static_cast<int32_t*>(std::malloc(tsize * sizeof(int32_t)));
    vert = static_cast<int32_t*>(std::malloc(tsize / 2 * sizeof(int32_t)));
    parent = static_cast<int32_t*>(std::malloc(tsize / 2 * sizeof(int32_t)));
    if (!table || !vert || !parent) return false;
    std::memset(table, 0xff, tsize * sizeof(int32_t));
    return true;
  }

  ~GrowTable() {
    std::free(table);
    std::free(vert);
    std::free(parent);
  }

  bool grow() {
    // Mutate members only after every allocation succeeds: a half-grown
    // state (doubled mask, old table) would make any later intern()
    // probe out of bounds if the caller retries after an OOM failure.
    const int64_t nsize = tsize * 2;
    int32_t* t = static_cast<int32_t*>(std::malloc(nsize * sizeof(int32_t)));
    int32_t* v2 = static_cast<int32_t*>(
        std::realloc(vert, nsize / 2 * sizeof(int32_t)));
    if (v2) vert = v2;
    int32_t* p2 = static_cast<int32_t*>(
        std::realloc(parent, nsize / 2 * sizeof(int32_t)));
    if (p2) parent = p2;
    if (!t || !v2 || !p2) { std::free(t); return false; }
    tsize = nsize;
    mask = nsize - 1;
    std::memset(t, 0xff, nsize * sizeof(int32_t));
    for (int32_t c = 0; c < count; ++c) {
      int64_t i = cs_hash(vert[c], mask);
      while (t[i] >= 0) i = (i + 1) & mask;
      t[i] = c;
    }
    std::free(table);
    table = t;
    return true;
  }

  // Local index of v, interning on first sight; -1 on allocation failure.
  inline int32_t intern(int32_t v) {
    int64_t i = cs_hash(v, mask);
    while (true) {
      const int32_t e = table[i];
      if (e < 0) {
        if (2 * static_cast<int64_t>(count + 1) >= tsize) {
          if (!grow()) return -1;
          return intern(v);
        }
        table[i] = count;
        vert[count] = v;
        parent[count] = count;
        return count++;
      }
      if (vert[e] == v) return e;
      i = (i + 1) & mask;
    }
  }
};

// (Re)build the probe table at size tsize from vert_of[0..count).
bool cs_rehash(CompactSession* s, int64_t tsize) {
  int32_t* t = static_cast<int32_t*>(std::malloc(tsize * sizeof(int32_t)));
  if (!t) return false;
  std::memset(t, 0xff, tsize * sizeof(int32_t));
  const int64_t mask = tsize - 1;
  for (int32_t c = 0; c < s->count; ++c) {
    const int32_t v = s->vert_of[c];
    if (v < 0) continue;  // rebuild hole (staged-but-unfolded cid)
    int64_t i = cs_hash(v, mask);
    while (t[i] >= 0) i = (i + 1) & mask;
    t[i] = c;
  }
  std::free(s->table);
  s->table = t;
  s->tsize = tsize;
  s->mask = mask;
  return true;
}

// Roll an assign call back to its pre-call state: drop the fresh
// entries and rebuild the probe table from the surviving ones. Error
// paths only. Returns false when even the rollback rehash cannot
// allocate (the probe table then still holds the dropped entries and
// the session must be discarded — every caller treats that as fatal).
bool cs_rollback(CompactSession* s, int32_t base) {
  if (s->count == base) return true;
  s->count = base;
  return cs_rehash(s, s->tsize);
}

}  // namespace

extern "C" {

void* compact_session_create(int32_t capacity) {
  CompactSession* s = new (std::nothrow) CompactSession();
  if (!s) return nullptr;
  s->capacity = capacity;
  s->vert_of = static_cast<int32_t*>(
      std::malloc(sizeof(int32_t) * (capacity > 0 ? capacity : 1)));
  if (!s->vert_of || !cs_rehash(s, 1024)) {
    std::free(s->vert_of);
    std::free(s->table);
    delete s;
    return nullptr;
  }
  return s;
}

void compact_session_destroy(void* h) {
  if (!h) return;
  CompactSession* s = static_cast<CompactSession*>(h);
  std::free(s->table);
  std::free(s->vert_of);
  delete s;
}

void compact_session_reset(void* h) {
  CompactSession* s = static_cast<CompactSession*>(h);
  s->count = 0;
  std::memset(s->table, 0xff, s->tsize * sizeof(int32_t));
}

int32_t compact_session_assigned(void* h) {
  return static_cast<CompactSession*>(h)->count;
}

// Assign cids to ids (fresh ids get count, count+1, ... in first-seen
// ARRAY order). Returns the pre-call count (the new block's base), or
// -1 on capacity overflow, -2 on a negative id, or -4 on allocation
// failure. Every error path rolls the session back to the pre-call
// state (atomic-assign contract).
int64_t compact_session_assign(void* h, const int32_t* ids, int64_t n,
                               int32_t* out_cids) {
  CompactSession* s = static_cast<CompactSession*>(h);
  const int32_t base = s->count;
  for (int64_t j = 0; j < n; ++j) {
    const int32_t v = ids[j];
    if (v < 0) {
      // cs_rehash treats negative vert_of entries as holes: a negative
      // id would silently fall out of the probe table at the next table
      // growth and later be re-assigned a second cid. Reject it.
      if (!cs_rollback(s, base)) return -4;
      return -2;
    }
    int64_t i = cs_hash(v, s->mask);
    int32_t e;
    while ((e = s->table[i]) >= 0 && s->vert_of[e] != v) {
      i = (i + 1) & s->mask;
    }
    if (e >= 0) {
      out_cids[j] = e;
      continue;
    }
    if (s->count >= s->capacity) {
      if (!cs_rollback(s, base)) return -4;
      return -1;
    }
    s->table[i] = s->count;
    s->vert_of[s->count] = v;
    out_cids[j] = s->count++;
    if (2 * static_cast<int64_t>(s->count) >= s->tsize) {
      if (!cs_rehash(s, s->tsize * 2)) {
        // Mid-call growth failure: roll back like the paths above so
        // the caller never observes a partial assign block.
        cs_rollback(s, base);
        return -4;
      }
    }
  }
  return base;
}

// Copy vert_of[from:to) (the fresh ids of an assign block) into out.
void compact_session_new_ids(void* h, int32_t from, int32_t to,
                             int32_t* out) {
  CompactSession* s = static_cast<CompactSession*>(h);
  std::memcpy(out, s->vert_of + from,
              sizeof(int32_t) * static_cast<size_t>(to - from));
}

// cids of already-assigned ids; unknown ids get -1. Returns the number
// of unknown ids.
int64_t compact_session_lookup(void* h, const int32_t* ids, int64_t n,
                               int32_t* out_cids) {
  CompactSession* s = static_cast<CompactSession*>(h);
  int64_t bad = 0;
  for (int64_t j = 0; j < n; ++j) {
    const int32_t v = ids[j];
    int64_t i = cs_hash(v, s->mask);
    int32_t e;
    while ((e = s->table[i]) >= 0 && s->vert_of[e] != v) {
      i = (i + 1) & s->mask;
    }
    out_cids[j] = e;
    if (e < 0) ++bad;
  }
  return bad;
}

// ---------------------------------------------------------------- //
// Fused unit-level forest codec (VERDICT r4 items 1+7): one call per
// merge-window unit replaces the per-chunk combine + numpy group
// combine + per-pair (v, ri) wire with
//
//   1. cache-BLOCKED level-1 forests: union-find over `block`-edge
//      slices whose intern tables stay cache-resident (the whole-chunk
//      table at 2^20-edge chunks is 32MB — DRAM-resident probes were
//      the measured cost; 2^18-edge blocks with software prefetch of
//      the next edges' hash slots measured fastest), emitting
//      (vertex, root) pairs;
//      [A direct-mapped duplicate-edge filter was tried and REMOVED:
//      with 68% hit rate it still slowed L1 ~1.5x, because duplicate
//      edges' intern probes hit already-hot cache lines while the
//      filter added one cold 512KB-random access per edge.]
//   2. one level-2 merge over the level-1 pairs (∝ touched vertices,
//      not edges) in a GEOMETRICALLY GROWN table — sizing it to the
//      pair count upfront (the LocalTable policy) put every probe in
//      a DRAM-sized table; growth keeps most probes in cache;
//   3. SEGMENT-format output: members grouped by component with the
//      component root placed FIRST in its segment. The device fold
//      reconstructs each pair's root-row index as its segment start
//      (cumsum of lengths), so the wire carries 4 bytes/pair + one
//      length per component instead of the 8-byte (v, ri) pair — the
//      H2D link is the pipeline's scarce resource.
//
// Pure function (no session state): cid assignment stays in the
// ordered compact_session_assign turn, so concurrent ingest workers
// keep the heavy combine parallel. Output members are global VERTEX
// ids; the caller remaps them to cids with one session.assign pass
// (order-preserving, so the segment structure is unchanged).
//
//   out_v   : member vertex ids, root-first per segment (cap >= touched)
//   out_len : segment lengths (cap >= segments)
//   out_counts[2]: {n_members, n_segments}
//
// Returns 0, -2 on a slot outside [0, n_v), -3 on cap overflow, -4 on
// allocation failure.
void* cc_unit_begin(void) {
  GrowTable* t2 = new (std::nothrow) GrowTable();
  if (!t2) return nullptr;
  if (!t2->init(1 << 17)) { delete t2; return nullptr; }
  return t2;
}

void cc_unit_destroy(void* h) {
  delete static_cast<GrowTable*>(h);
}

int64_t cc_unit_members(void* h) {
  return static_cast<GrowTable*>(h)->count;
}

// Fold one buffer of edges into the unit forest: cache-blocked level-1
// union-find (per-block LocalTable + next-edge hash-slot prefetch), each
// block's (vertex, root) pairs interned straight into the unit's growing
// level-2 table. Callable repeatedly per unit — the caller streams its
// chunk buffers without concatenating them.
int cc_unit_add(void* h, const int32_t* src, const int32_t* dst,
                const uint8_t* valid, int64_t n, int32_t n_v,
                int64_t block) {
  GrowTable* t2 = static_cast<GrowTable*>(h);
  if (block <= 0) block = 1 << 18;
  for (int64_t lo = 0; lo < n; lo += block) {
    const int64_t hi = lo + block < n ? lo + block : n;
    LocalTable t;
    if (!t.init(hi - lo)) return -4;
    for (int64_t i = lo; i < hi; ++i) {
      if (i + 8 < hi) {
        // Hide the table-probe latency of edge i+8 behind edge i's
        // work (the intern loop is latency-bound on its first probe).
        __builtin_prefetch(
            &t.table[(static_cast<uint32_t>(src[i + 8]) * 2654435761u)
                     & t.mask]);
        __builtin_prefetch(
            &t.table[(static_cast<uint32_t>(dst[i + 8]) * 2654435761u)
                     & t.mask]);
      }
      if (valid != nullptr && !valid[i]) continue;
      const int32_t u = src[i];
      const int32_t v = dst[i];
      if (u < 0 || u >= n_v || v < 0 || v >= n_v) return -2;
      const int32_t lu = t.intern(u);
      const int32_t lv = t.intern(v);
      const int32_t ru = find_root(t.parent, lu);
      const int32_t rv = find_root(t.parent, lv);
      if (ru != rv) {
        if (t.vert[ru] < t.vert[rv]) t.parent[rv] = ru;
        else t.parent[ru] = rv;
      }
    }
    for (int32_t j = 0; j < t.count; ++j) {
      const int32_t lu = t2->intern(t.vert[j]);
      const int32_t lv = t2->intern(t.vert[find_root(t.parent, j)]);
      if (lu < 0 || lv < 0) return -4;
      const int32_t ru = find_root(t2->parent, lu);
      const int32_t rv = find_root(t2->parent, lv);
      if (ru != rv) {
        if (t2->vert[ru] < t2->vert[rv]) t2->parent[rv] = ru;
        else t2->parent[ru] = rv;
      }
    }
  }
  return 0;
}

// Emit the unit forest in segment format and leave the builder empty of
// output obligations (the caller destroys it). Segments are numbered by
// first-touch of their root; the root entry goes FIRST in its segment
// (the device derives each pair's root-row index as its segment start).
int cc_unit_finish(void* h, int32_t* out_v, int64_t cap_v,
                   int32_t* out_len, int64_t cap_len,
                   int64_t* out_counts) {
  GrowTable* t2 = static_cast<GrowTable*>(h);
  out_counts[0] = 0;
  out_counts[1] = 0;
  const int32_t count = t2->count;
  if (count > cap_v) return -3;
  int32_t* rloc = static_cast<int32_t*>(std::malloc(
      sizeof(int32_t) * (count > 0 ? count : 1)));
  int32_t* segof = static_cast<int32_t*>(std::malloc(
      sizeof(int32_t) * (count > 0 ? count : 1)));
  if (!rloc || !segof) { std::free(rloc); std::free(segof); return -4; }
  std::memset(segof, 0xff, sizeof(int32_t) * (count > 0 ? count : 1));
  int32_t nseg = 0;
  for (int32_t j = 0; j < count; ++j) {
    rloc[j] = find_root(t2->parent, j);
    if (segof[rloc[j]] < 0) {
      if (nseg >= cap_len) { std::free(rloc); std::free(segof); return -3; }
      segof[rloc[j]] = nseg++;
    }
  }
  int32_t* start = static_cast<int32_t*>(std::malloc(
      sizeof(int32_t) * (nseg > 0 ? nseg : 1)));
  if (!start) { std::free(rloc); std::free(segof); return -4; }
  std::memset(start, 0, sizeof(int32_t) * (nseg > 0 ? nseg : 1));
  for (int32_t j = 0; j < count; ++j) start[segof[rloc[j]]] += 1;
  int32_t acc = 0;
  for (int32_t s = 0; s < nseg; ++s) {
    out_len[s] = start[s];
    const int32_t c = start[s];
    start[s] = acc;
    acc += c;
  }
  // Two-pass fill: roots at their segment starts first, then members
  // appended from start+1 onward (start[] doubles as the fill cursor).
  for (int32_t j = 0; j < count; ++j) {
    if (j == rloc[j]) out_v[start[segof[j]]] = t2->vert[j];
  }
  for (int32_t s = 0; s < nseg; ++s) start[s] += 1;
  for (int32_t j = 0; j < count; ++j) {
    if (j != rloc[j]) out_v[start[segof[rloc[j]]]++] = t2->vert[j];
  }
  std::free(rloc);
  std::free(segof);
  std::free(start);
  out_counts[0] = count;
  out_counts[1] = nseg;
  return 0;
}

// One-shot convenience wrapper over begin/add/finish (single buffer).
int cc_unit_forest_segments(const int32_t* src, const int32_t* dst,
                            const uint8_t* valid, int64_t n, int32_t n_v,
                            int64_t block, int32_t* out_v, int64_t cap_v,
                            int32_t* out_len, int64_t cap_len,
                            int64_t* out_counts) {
  void* h = cc_unit_begin();
  if (!h) return -4;
  int rc = cc_unit_add(h, src, dst, valid, n, n_v, block);
  if (rc == 0) rc = cc_unit_finish(h, out_v, cap_v, out_len, cap_len,
                                   out_counts);
  cc_unit_destroy(h);
  return rc;
}

// Restore from a checkpointed vertex_of array (vertex_of[cid] = global
// id, -1 for unassigned): count resumes past the highest recorded cid;
// holes stay dead. Returns 0, -1 when the checkpoint exceeds the
// session capacity (truncating would drop assignments and later
// re-issue those cids), or -4 on allocation failure.
int compact_session_rebuild(void* h, const int32_t* vertex_of, int32_t m) {
  CompactSession* s = static_cast<CompactSession*>(h);
  if (m > s->capacity) return -1;
  int32_t hi = -1;
  for (int32_t c = 0; c < m; ++c) {
    s->vert_of[c] = vertex_of[c];
    if (vertex_of[c] >= 0) hi = c;
  }
  s->count = hi + 1;
  int64_t tsize = s->tsize;
  while (2 * static_cast<int64_t>(s->count) >= tsize) tsize *= 2;
  if (!cs_rehash(s, tsize)) return -4;
  return 0;
}

}  // extern "C"
