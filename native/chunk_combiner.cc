// Native ingest-side chunk combiners.
//
// The reference pre-aggregates per partition before the global merge
// (SummaryBulkAggregation's per-partition window fold,
// /root/reference/src/main/java/org/apache/flink/graph/streaming/SummaryBulkAggregation.java:76-80,
// folding DisjointSet.union per edge, .../summaries/DisjointSet.java:92-118).
// On TPU the ingest link (host->device) is the scarce resource, so the same
// partial aggregation runs *before* the transfer: a chunk of E edges is
// reduced to its spanning forest — at most min(E, n_v) (vertex, root) pairs,
// shipped as a dense i32 label array. Connectivity is preserved exactly;
// bytes-per-edge on the wire drops by 1-2 orders of magnitude.
//
// Exposed via ctypes (gelly_tpu/utils/native.py); no pybind dependency.

#include <cstdint>
#include <cstring>

namespace {

// Find with path halving: keeps trees near-flat without recursion.
inline int32_t find_root(int32_t* p, int32_t x) {
  while (p[x] != x) {
    p[x] = p[p[x]];
    x = p[x];
  }
  return x;
}

// Parity-carrying find with parity-aware path halving: before hopping to
// the grandparent, fold the parent's edge parity into this node's so
// parity[x] always means "to labels[x]". Without halving, union-by-min
// grows long chains on skewed streams (~6x slower). *p_out receives the
// parity from x to its root.
inline int32_t parity_find(int32_t* labels, uint8_t* parity, int32_t x,
                           uint8_t* p_out) {
  uint8_t acc = 0;
  while (labels[x] != x) {
    const int32_t par = labels[x];
    if (labels[par] != par) {
      parity[x] = static_cast<uint8_t>(parity[x] ^ parity[par]);
      labels[x] = labels[par];
    }
    acc ^= parity[x];
    x = labels[x];
  }
  *p_out = acc;
  return x;
}

}  // namespace

extern "C" {

// Union-find over one chunk's valid edges.
//
//   labels[v] = component root slot for every vertex touched by the chunk,
//   labels[v] = -1 for untouched slots.
//
// Roots are canonicalized to the minimum slot in the chunk-local component,
// matching the device kernel's min-root convention
// (gelly_tpu/ops/unionfind.py) so the downstream union of (v, labels[v])
// star edges is already near-flat.
//
// Returns 0 on success, 2 if any valid edge has a slot outside [0, n_v).
int cc_chunk_combine(const int32_t* src, const int32_t* dst,
                     const uint8_t* valid, int64_t n, int32_t n_v,
                     int32_t* labels) {
  // labels doubles as the parent array during the pass.
  std::memset(labels, 0xff, sizeof(int32_t) * static_cast<size_t>(n_v));
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) continue;
    const int32_t u = src[i];
    const int32_t v = dst[i];
    if (u < 0 || u >= n_v || v < 0 || v >= n_v) return 2;
    if (labels[u] < 0) labels[u] = u;
    if (labels[v] < 0) labels[v] = v;
    const int32_t ru = find_root(labels, u);
    const int32_t rv = find_root(labels, v);
    if (ru != rv) {
      // Union by min root: canonical representative = min slot.
      if (ru < rv) {
        labels[rv] = ru;
      } else {
        labels[ru] = rv;
      }
    }
  }
  // Flatten: every touched vertex points directly at its root.
  for (int32_t v = 0; v < n_v; ++v) {
    if (labels[v] >= 0) labels[v] = find_root(labels, v);
  }
  return 0;
}

// Parity (bipartiteness) variant: same spanning-forest compression but each
// vertex also carries the XOR parity of its path to the root — enough to
// reconstruct the 2-coloring constraints of the chunk (the Candidates sign
// logic, .../summaries/Candidates.java:61-74). An odd cycle inside the chunk
// sets *conflict to 1 (the chunk alone is non-bipartite).
//
//   labels[v]  = root slot or -1
//   parity[v]  = path parity to root (0/1), valid where labels[v] >= 0
int parity_chunk_combine(const int32_t* src, const int32_t* dst,
                         const uint8_t* valid, int64_t n, int32_t n_v,
                         int32_t* labels, uint8_t* parity,
                         int32_t* conflict) {
  std::memset(labels, 0xff, sizeof(int32_t) * static_cast<size_t>(n_v));
  std::memset(parity, 0, static_cast<size_t>(n_v));
  *conflict = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) continue;
    const int32_t u = src[i];
    const int32_t v = dst[i];
    if (u < 0 || u >= n_v || v < 0 || v >= n_v) return 2;
    if (labels[u] < 0) { labels[u] = u; parity[u] = 0; }
    if (labels[v] < 0) { labels[v] = v; parity[v] = 0; }
    uint8_t pu, pv;
    const int32_t ru = parity_find(labels, parity, u, &pu);
    const int32_t rv = parity_find(labels, parity, v, &pv);
    if (ru == rv) {
      if (pu == pv) *conflict = 1;  // odd cycle
      continue;
    }
    if (ru < rv) {
      labels[rv] = ru;
      parity[rv] = static_cast<uint8_t>(pu ^ pv ^ 1);
    } else {
      labels[ru] = rv;
      parity[ru] = static_cast<uint8_t>(pu ^ pv ^ 1);
    }
  }
  // Flatten labels and parities together (two passes of pointer chase are
  // bounded by tree height; height is small after union-by-min + the
  // root-ward writes above, and this pass fully flattens).
  for (int32_t v = 0; v < n_v; ++v) {
    if (labels[v] < 0) continue;
    int32_t r = v; uint8_t p = 0;
    while (labels[r] != r) { p ^= parity[r]; r = labels[r]; }
    labels[v] = r;
    parity[v] = p;
  }
  return 0;
}

// Degree-delta codec: one pass over the chunk accumulating the ±1 endpoint
// deltas (EventType deletions subtract) into a dense i32[n_v] vector — the
// degree equivalent of the forest payloads above (DegreeMapFunction
// semantics, .../SimpleEdgeStream.java:461-478, with DegreeDistribution's
// ±1 deletion handling, .../example/DegreeDistribution.java:70-79). The
// n_v-sized delta vector is what ships over the wire instead of the edges.
//
//   event : optional i8[n] (null = all additions), 1 = deletion
//   valid : optional u8[n] mask (null = all valid)
//
// Returns 0 on success, 2 on a slot outside [0, n_v).
int degree_chunk_deltas(const int32_t* src, const int32_t* dst,
                        const int8_t* event, const uint8_t* valid,
                        int64_t n, int32_t n_v, int32_t count_out,
                        int32_t count_in, int32_t* out) {
  std::memset(out, 0, sizeof(int32_t) * static_cast<size_t>(n_v));
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) continue;
    const int32_t d = (event != nullptr && event[i] == 1) ? -1 : 1;
    if (count_out) {
      const int32_t u = src[i];
      if (u < 0 || u >= n_v) return 2;
      out[u] += d;
    }
    if (count_in) {
      const int32_t v = dst[i];
      if (v < 0 || v >= n_v) return 2;
      out[v] += d;
    }
  }
  return 0;
}

}  // extern "C"
