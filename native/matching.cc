// Native centralized greedy weighted-matching stage.
//
// The reference runs greedy ½-approximate weighted matching as one
// parallelism-1 stateful operator: a new edge evicts its colliding matched
// edges iff its weight exceeds twice their combined weight
// (/root/reference/src/main/java/org/apache/flink/graph/streaming/example/
// CentralizedWeightedMatching.java:76-107). The decision chain is strictly
// sequential per edge, so it belongs on the host — this kernel is the native
// runtime stage behind gelly_tpu/library/matching.py's host path (the
// per-edge Python loop remains as the fallback).
//
// State layout mirrors the Python host path exactly: partner[i32 n_v]
// (-1 = unmatched) and weight[f64 n_v] (the matched edge's weight stored at
// both endpoints). All weight arithmetic is double, like the reference's
// Java doubles — the Python fallback keeps its state in float64 for the
// same reason.
//
// Exposed via ctypes (gelly_tpu/utils/native.py); no pybind dependency.

#include <cstdint>

extern "C" {

// Fold one chunk of edges into the matching state, in stream order.
//
//   src/dst : dense vertex slots, i32[n]
//   w       : edge weights, f64[n] (chunk values promoted by the caller)
//   valid   : optional u8 mask (null = all valid)
//   partner : i32[n_v] in/out, -1 = unmatched
//   weight  : f64[n_v] in/out
//
// Event emission (optional, all-or-nothing): when ev_type != null, every
// accepted edge appends up to two REMOVE records (type 1, pair (x, partner
// of x), weight of the evicted edge) followed by one ADD record (type 0,
// (u, v), w) — the reference's MatchingEvent collector output
// (CentralizedWeightedMatching.java:99-104). Buffers must hold ev_cap
// records; *ev_count receives the number written.
//
// Returns 0 on success, 2 on a slot outside [0, n_v), 3 on event overflow
// (cannot happen with ev_cap >= 3n).
int matching_chunk_fold(const int32_t* src, const int32_t* dst,
                        const double* w, const uint8_t* valid, int64_t n,
                        int32_t n_v, int32_t* partner, double* weight,
                        uint8_t* ev_type, int32_t* ev_a, int32_t* ev_b,
                        double* ev_w, int64_t ev_cap, int64_t* ev_count) {
  int64_t ne = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) continue;
    const int32_t u = src[i];
    const int32_t v = dst[i];
    if (u < 0 || u >= n_v || v < 0 || v >= n_v) return 2;
    if (u == v) continue;
    const int32_t pu = partner[u];
    const int32_t pv = partner[v];
    // Colliding matched edges: u's and v's. If u and v are matched to each
    // other that is one edge, not two.
    const bool same = (pu == v) && (pv == u);
    const double coll_sum =
        same ? weight[u]
             : (pu >= 0 ? weight[u] : 0.0) + (pv >= 0 ? weight[v] : 0.0);
    if (w[i] > 2.0 * coll_sum) {
      const int32_t evict_x[2] = {u, v};
      const int32_t evict_p[2] = {pu, pv};
      const int n_evict = same ? 1 : 2;
      for (int k = 0; k < n_evict; ++k) {
        const int32_t x = evict_x[k];
        const int32_t px = evict_p[k];
        if (px >= 0) {
          if (ev_type != nullptr) {
            if (ne >= ev_cap) return 3;
            ev_type[ne] = 1;  // REMOVE
            ev_a[ne] = x;
            ev_b[ne] = px;
            ev_w[ne] = weight[x];
            ++ne;
          }
          partner[px] = -1;
          weight[px] = 0.0;
          partner[x] = -1;
          weight[x] = 0.0;
        }
      }
      partner[u] = v;
      partner[v] = u;
      weight[u] = weight[v] = w[i];
      if (ev_type != nullptr) {
        if (ne >= ev_cap) return 3;
        ev_type[ne] = 0;  // ADD
        ev_a[ne] = u;
        ev_b[ne] = v;
        ev_w[ne] = w[i];
        ++ne;
      }
    }
  }
  if (ev_count != nullptr) *ev_count = ne;
  return 0;
}

}  // extern "C"
