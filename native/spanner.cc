// Native greedy k-spanner fold (centralized host stage).
//
// The reference's spanner keeps an edge iff its endpoints are not already
// within k hops of each other in the spanner built so far
// (/root/reference/src/main/java/org/apache/flink/graph/streaming/library/
// Spanner.java:70-77, boundedBFS in summaries/AdjacencyListGraph.java:79-116).
// The per-edge decision is order-dependent and strictly sequential, the
// same scalar-state-machine shape as the weighted-matching stage — so the
// hot fold belongs on the host: the device lax.scan pays a k-round
// frontier expansion over the whole adjacency per edge (~5k edges/s),
// while this kernel runs a bounded BFS over capped-degree rows per edge.
//
// State is owned by the caller as flat arrays (mutated in place), matching
// the sparse device summary's layout so results are comparable:
//   nbr   : i32[n_v * max_degree] adjacency rows, -1 = empty
//   deg   : i32[n_v]
//   stamp : i32[n_v]  BFS visit stamps, init 0
//   meta  : i64[3]    {stamp_counter, n_accepted, deg_overflow}
//
// Degree-cap overflows drop the row insert and count it (meta[2]) — the
// adjacency then under-reports reachability, which can only ACCEPT an
// extra edge, never reject wrongly, so the k-stretch bound survives (the
// same conservative degradation as the sparse device path).
//
// Exposed via ctypes (gelly_tpu/utils/native.py); no pybind dependency.

#include <cstdint>
#include <cstring>
#include <memory>

namespace {

// dist(u, v) <= k over capped-degree rows: depth-bounded BFS with stamp
// marking; q is scratch of n_v slots (always written before read).
inline bool within_k(const int32_t* nbr, const int32_t* deg, int32_t* stamp,
                     int32_t cur, int32_t max_degree, int32_t u, int32_t v,
                     int32_t k, int32_t* q) {
  if (u == v) return true;
  int64_t head = 0, tail = 0;
  q[tail++] = u;
  stamp[u] = cur;
  int64_t level_end = tail;
  int32_t depth = 0;
  while (head < tail && depth < k) {
    const int32_t x = q[head++];
    const int32_t* row = nbr + static_cast<int64_t>(x) * max_degree;
    const int32_t dx = deg[x];
    for (int32_t j = 0; j < dx; ++j) {
      const int32_t y = row[j];
      if (y == v) return true;
      if (stamp[y] != cur) {
        stamp[y] = cur;
        q[tail++] = y;
      }
    }
    if (head == level_end) {  // finished this BFS level
      ++depth;
      level_end = tail;
    }
  }
  return false;
}

}  // namespace

extern "C" {

// Fold one chunk of edges into the spanner, in stream order. Accepted
// edges are appended to out_src/out_dst starting at meta[1].
//
// Returns 0 on success, 2 on a slot outside [0, n_v), 3 when the output
// edge list is full (sticky: the caller records overflow; the adjacency
// was NOT updated for the overflowing edge, so state stays consistent
// with the emitted list).
int spanner_chunk_fold(const int32_t* src, const int32_t* dst,
                       const uint8_t* valid, int64_t n, int32_t n_v,
                       int32_t k, int32_t max_degree,
                       int32_t* nbr, int32_t* deg, int32_t* stamp,
                       int64_t* meta,
                       int32_t* out_src, int32_t* out_dst, int64_t out_cap) {
  // Uninitialized scratch: every q slot is written before it is read, and
  // zero-filling n_v ints per chunk call is pure waste at N >= 1M.
  std::unique_ptr<int32_t[]> q(new int32_t[static_cast<size_t>(n_v)]);
  for (int64_t i = 0; i < n; ++i) {
    if (valid != nullptr && !valid[i]) continue;
    const int32_t u = src[i];
    const int32_t v = dst[i];
    if (u < 0 || u >= n_v || v < 0 || v >= n_v) return 2;
    if (u == v) continue;
    // Stamp space: reset before wrap (stamps are i32; one per query).
    if (meta[0] >= INT32_MAX - 1) {
      std::memset(stamp, 0, sizeof(int32_t) * static_cast<size_t>(n_v));
      meta[0] = 0;
    }
    const int32_t cur = static_cast<int32_t>(++meta[0]);
    if (within_k(nbr, deg, stamp, cur, max_degree, u, v, k, q.get())) continue;
    if (meta[1] >= out_cap) return 3;
    out_src[meta[1]] = u;
    out_dst[meta[1]] = v;
    ++meta[1];
    for (int t = 0; t < 2; ++t) {
      const int32_t a = t ? v : u;
      const int32_t b = t ? u : v;
      if (deg[a] < max_degree) {
        nbr[static_cast<int64_t>(a) * max_degree + deg[a]] = b;
        ++deg[a];
      } else {
        ++meta[2];
      }
    }
  }
  return 0;
}

}  // extern "C"
