// Native edge-list parser — the ingest hot path.
//
// The reference delegates text ingestion to Flink/JVM readers plus per-line
// Java map functions (e.g. ExactTriangleCount.java:183-192,
// ConnectedComponentsExample.java:105-118: split on whitespace, skip '%'
// comments). This framework owns its runtime natively: a single-pass
// byte-scanning parser (no line splitting, no regex) feeding int64 COO
// buffers that Python wraps zero-copy via ctypes/numpy.
//
// Exposed C ABI (consumed by gelly_tpu/utils/native.py):
//   parse_edge_list(path, &src, &dst, &val, want_vals, &n) -> 0 on success
//   free_edge_buffers(src, dst, val)

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace {

// Grows-by-doubling int64/double buffers.
struct Buf {
  void* data = nullptr;
  size_t len = 0;
  size_t cap = 0;

  bool push_i64(int64_t v) {
    if (len == cap) {
      size_t ncap = cap ? cap * 2 : 1 << 16;
      void* nd = realloc(data, ncap * sizeof(int64_t));
      if (!nd) return false;
      data = nd;
      cap = ncap;
    }
    static_cast<int64_t*>(data)[len++] = v;
    return true;
  }
  bool push_f64(double v) {
    if (len == cap) {
      size_t ncap = cap ? cap * 2 : 1 << 16;
      void* nd = realloc(data, ncap * sizeof(double));
      if (!nd) return false;
      data = nd;
      cap = ncap;
    }
    static_cast<double*>(data)[len++] = v;
    return true;
  }
};

inline bool at_token_end(const char* p, const char* end) {
  // A numeric token must terminate at whitespace/EOL/EOF — '2x' is not an
  // id (python-parser parity: int("2x") raises and the line is skipped).
  return p >= end || *p == ' ' || *p == '\t' || *p == '\r' || *p == '\n';
}

inline const char* skip_ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  return p;
}

inline const char* skip_line(const char* p, const char* end) {
  while (p < end && *p != '\n') ++p;
  return p < end ? p + 1 : end;
}

// Parses a signed integer; returns nullptr if none present.
inline const char* parse_i64(const char* p, const char* end, int64_t* out) {
  bool neg = false;
  if (p < end && (*p == '-' || *p == '+')) {
    neg = (*p == '-');
    ++p;
  }
  if (p >= end || *p < '0' || *p > '9') return nullptr;
  // Unsigned magnitude accumulation: the negative range reaches one past
  // INT64_MAX, so INT64_MIN itself must parse (python-parser parity) while
  // anything wider reads as malformed, never wrapping to a wrong id.
  const uint64_t limit =
      static_cast<uint64_t>(INT64_MAX) + (neg ? 1u : 0u);
  uint64_t v = 0;
  while (p < end && *p >= '0' && *p <= '9') {
    const uint64_t digit = static_cast<uint64_t>(*p - '0');
    if (v > (limit - digit) / 10) return nullptr;
    v = v * 10 + digit;
    ++p;
  }
  *out = neg ? static_cast<int64_t>(0u - v) : static_cast<int64_t>(v);
  return p;
}

}  // namespace

extern "C" {

// Returns 0 on success; 1 file error; 2 allocation failure.
int parse_edge_list(const char* path, int64_t** src_out, int64_t** dst_out,
                    double** val_out, int want_vals, int64_t* n_out) {
  FILE* f = fopen(path, "rb");
  if (!f) return 1;
  fseek(f, 0, SEEK_END);
  long fsize = ftell(f);
  fseek(f, 0, SEEK_SET);
  char* text = static_cast<char*>(malloc(fsize + 1));
  if (!text) {
    fclose(f);
    return 2;
  }
  size_t got = fread(text, 1, fsize, f);
  fclose(f);
  text[got] = '\0';  // strtod guard: parsing never runs past the buffer

  Buf src, dst, val;
  const char* p = text;
  const char* end = text + got;
  int rc = 0;
  while (p < end) {
    p = skip_ws(p, end);
    if (p >= end) break;
    if (*p == '\n') {
      ++p;
      continue;
    }
    if (*p == '%' || *p == '#') {
      p = skip_line(p, end);
      continue;
    }
    int64_t a, b;
    const char* q = parse_i64(p, end, &a);
    if (!q || !at_token_end(q, end)) {
      p = skip_line(p, end);  // malformed line: skip (parser parity with
      continue;               // the examples' lenient split-and-parse)
    }
    q = skip_ws(q, end);
    q = parse_i64(q, end, &b);
    if (!q || !at_token_end(q, end)) {
      p = skip_line(p, end);
      continue;
    }
    if (!src.push_i64(a) || !dst.push_i64(b)) {
      rc = 2;
      break;
    }
    if (want_vals) {
      q = skip_ws(q, end);
      double v = 1.0;
      // Full float grammar via strtod (exponents, leading dot, sign) —
      // python-parser parity: float(fields[2]), defaulting to 1.0 when the
      // column is missing or malformed. The buffer is NUL-terminated and
      // strtod stops at the first invalid char, so it cannot run past a
      // line boundary (newlines terminate parsing).
      if (q < end && *q != '\n') {
        char* vend = nullptr;
        double parsed = strtod(q, &vend);
        if (vend != q && at_token_end(vend, end)) {
          v = parsed;
          q = vend;
        }
      }
      if (!val.push_f64(v)) {
        rc = 2;
        break;
      }
    }
    p = skip_line(q ? q : p, end);
  }
  free(text);
  if (rc != 0) {
    free(src.data);
    free(dst.data);
    free(val.data);
    return rc;
  }
  *src_out = static_cast<int64_t*>(src.data);
  *dst_out = static_cast<int64_t*>(dst.data);
  *val_out = want_vals ? static_cast<double*>(val.data) : nullptr;
  *n_out = static_cast<int64_t>(src.len);
  return 0;
}

void free_edge_buffers(int64_t* src, int64_t* dst, double* val) {
  free(src);
  free(dst);
  free(val);
}

}  // extern "C"
